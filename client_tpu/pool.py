"""Health-aware multi-endpoint pool: failover, hedging, outlier ejection.

PR 1's resilience layer makes a *single* endpoint survivable; production
deployments front a fleet of replica servers and need the client to keep
working when one of them dies, degrades, or drains. This module is that
layer: an :class:`EndpointPool` (the transport-free health/routing engine)
plus :class:`PoolClient` / :class:`AioPoolClient` wrappers exposing the
familiar ``InferenceServerClient`` API over N server URLs — constructible
over all four frontends (HTTP sync/aio, GRPC sync/aio)::

    from client_tpu.pool import PoolClient

    client = PoolClient(["10.0.0.1:8000", "10.0.0.2:8000"], protocol="http")
    client.infer("simple", inputs)          # routed, failed over, hedged
    client.endpoint_stats()                 # per-endpoint snapshot

What the pool provides:

- **Active health probing** — a background prober calls each endpoint's
  ``is_server_ready(probe=True)`` (the KServe v2 ready endpoint in
  probe mode: connect-class failures return ``False`` instead of raising)
  every ``health_interval_s``; an unready endpoint stops receiving traffic
  until the probe succeeds again. A *draining* replica (ready flipped
  false, still serving) is routed away from before its socket disappears.
- **Passive outlier ejection** — ``resilience.classify_fault`` outcomes
  feed per-endpoint consecutive-failure counters; ``eject_after``
  consecutive transport failures eject the endpoint for an exponentially
  growing window (``base_ejection_s * multiplier^k``, capped at
  ``max_ejection_s``), Envoy-style. At most ``ceil(N/2)`` replicas are
  ever ejected at once — the pool degrades before it self-blinds.
- **Routing policies** — ``round_robin``, ``least_outstanding``,
  ``weighted`` (smooth weighted round-robin over static weights), and
  ``orca_weighted`` (smooth-WRR over weights derived from the servers'
  TTL-fresh ORCA ``endpoint-load-metrics`` reports, hysteresis-smoothed,
  falling back to least-outstanding whenever any replica's load is stale
  or absent), each honoring health, ejection, the per-endpoint
  :class:`~client_tpu.resilience.CircuitBreaker` (an endpoint whose
  breaker is open is never selected; a half-open endpoint receives
  exactly the probes its breaker admits) and, when armed, the
  per-endpoint adaptive concurrency limit.
- **Admission control** — ``admission=`` installs a pool-level
  :class:`~client_tpu.admission.AdmissionController` (adaptive limiter +
  priority lanes + deadline-aware shedding): one token covers the whole
  failover/hedge run, saturated requests raise the typed
  ``AdmissionRejected`` (counted as *shed*, never error), and
  ``endpoint_limits=`` adds a per-replica adaptive limit that selection
  honors like a breaker (docs/admission.md).
- **Transparent failover** — one shared
  :class:`~client_tpu.resilience.AttemptBudget` deadline across replicas;
  re-attempts obey PR 1's idempotency rule: a sequence request
  (``sequence_id != 0``) whose in-flight attempt died is NEVER silently
  re-sent to another replica — a typed :class:`SequenceAbandoned` event
  is delivered to ``on_event`` and the original error raises.
- **Hedged requests** — for idempotent infers with hedging armed, the
  request is issued to a second replica after a hedge delay (default:
  the rolling p95 of recent pool latencies, plus injectable-rng jitter);
  the first success wins and the loser is cancelled (true cancellation
  on asyncio, best-effort on threads). Sequence requests never hedge.

GRPC bidi streams are NOT pooled: ``start_stream`` selects one endpoint
and PINS the stream there — ``async_stream_infer`` / ``stop_stream``
route to that same endpoint until the stream stops (use
``auto_reconnect`` from PR 1 for same-endpoint stream recovery).

Server-side *state* is fleet state: registration/admin mutators
(``register_*`` / ``unregister_*`` / ``load_model`` / ``unload_model`` /
``update_*`` settings, plus client plugins) are BROADCAST to every
endpoint instead of landing on one arbitrary replica; read-only calls
delegate to a single healthy endpoint under the failover engine.
"""

from __future__ import annotations

import asyncio
import hashlib
import inspect
import math
import random
import threading
import time
from collections import deque
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from typing import Any, Callable, Dict, List, Optional, Sequence

from . import flight as _flight
from ._base import (
    INFER_POSITIONAL_PREFIX,
    consume_admission_phase,
    fold_infer_args,
    stash_admission_phase,
)
from .admission import (
    AdaptiveLimiter,
    AdmissionController,
    AdmissionRejected,
    SHED_ENDPOINT_SATURATED,
)
from .resilience import (
    CONNECT,
    FATAL,
    INVALID,
    SHED,
    TIMEOUT,
    TRANSIENT,
    AttemptBudget,
    CircuitBreaker,
    CircuitOpenError,
    ResiliencePolicy,
    RetryPolicy,
    classify_fault,
)
from .utils import InferenceServerException, sorted_percentile

__all__ = [
    "ROUND_ROBIN",
    "LEAST_OUTSTANDING",
    "WEIGHTED",
    "ORCA_WEIGHTED",
    "AFFINITY",
    "AioPoolClient",
    "EndpointEjected",
    "EndpointHealthChanged",
    "EndpointPool",
    "EndpointQuarantined",
    "EndpointReadmitted",
    "EndpointSpec",
    "HedgePolicy",
    "NoEndpointAvailableError",
    "PoolClient",
    "RoleFallback",
    "SequenceAbandoned",
    "load_score",
]

ROUND_ROBIN = "round_robin"
LEAST_OUTSTANDING = "least_outstanding"
WEIGHTED = "weighted"
ORCA_WEIGHTED = "orca_weighted"
AFFINITY = "affinity"
_ROUTING_POLICIES = (ROUND_ROBIN, LEAST_OUTSTANDING, WEIGHTED, ORCA_WEIGHTED,
                     AFFINITY)

# orca_weighted tuning: the weight floor keeps a slammed replica barely
# in rotation (so its load reports keep flowing and recovery is visible);
# hysteresis ignores weight moves smaller than this fraction of the old
# weight (ORCA reports arrive per-response — routing must not thrash on
# report-to-report jitter); smoothing is the EWMA step for moves that DO
# clear the hysteresis band
_ORCA_WEIGHT_FLOOR = 0.05
_ORCA_HYSTERESIS = 0.10
_ORCA_SMOOTHING = 0.5
# utilization dominates the blend when both signals exist; qps fills in
# relative pressure between replicas reporting equal utilization
_ORCA_QPS_BLEND = 0.3

# affinity routing: a key's home may carry at most ``bound * fair-share``
# outstanding requests before the key deterministically spills to the
# next endpoint in its rendezvous order (bounded-load consistent hashing:
# a drowned home sheds overflow instead of queueing hot keys behind it)
_AFFINITY_BOUND = 2.0
# per-endpoint distinct-key tracking cap (doctor's affinity_skew signal);
# past it the count saturates rather than growing without bound
_AFFINITY_KEY_CAP = 2048


def _affinity_ranked(key_digest: bytes,
                     endpoints: Sequence["EndpointState"],
                     ) -> List["EndpointState"]:
    """Rendezvous (highest-random-weight) order of ``endpoints`` for one
    key: a pure function of (key, url) — every client ranks identically,
    and removing an endpoint never re-homes keys owned by the others."""
    return sorted(
        endpoints,
        key=lambda ep: hashlib.blake2b(
            key_digest + ep.url.encode(), digest_size=8).digest(),
        reverse=True)


def load_score(load, max_qps: Optional[float] = None,
               max_busy_us: Optional[float] = None) -> Optional[float]:
    """One ORCA report -> a busy score in [0, 1] (higher = more loaded).

    Prefers the standard ORCA utilization signals
    (``application_utilization``, ``cpu_utilization``, or the max over a
    ``utilization.*`` map), blended with relative QPS
    (``rps_fractional``/``qps`` against the fleet max) when present.
    Falls back to the in-repo server's
    ``named_metrics.avg_compute_infer_us`` (relative to the fleet max) so
    orca_weighted works against servers that report busy-time rather
    than utilization. Returns None when the report carries no usable
    signal."""
    metrics = load.metrics
    util = metrics.get("application_utilization")
    if util is None:
        util = metrics.get("cpu_utilization")
    if util is None:
        subs = [v for k, v in metrics.items() if k.startswith("utilization")]
        util = max(subs) if subs else None
    qps = metrics.get("rps_fractional", metrics.get("qps"))
    qps_norm = (qps / max_qps if qps is not None and max_qps else None)
    if util is not None:
        util = min(max(float(util), 0.0), 1.0)
        if qps_norm is not None:
            return ((1.0 - _ORCA_QPS_BLEND) * util
                    + _ORCA_QPS_BLEND * min(max(qps_norm, 0.0), 1.0))
        return util
    if qps_norm is not None:
        return min(max(qps_norm, 0.0), 1.0)
    busy = metrics.get("named_metrics.avg_compute_infer_us")
    if busy is not None and max_busy_us:
        return min(max(float(busy) / max_busy_us, 0.0), 1.0)
    return None


class NoEndpointAvailableError(InferenceServerException):
    """Every endpoint is ejected/unhealthy/breaker-open (or excluded)."""

    def __init__(self, msg: str = "no endpoint available in the pool"):
        super().__init__(msg, status="POOL_EXHAUSTED")


# -- typed pool events --------------------------------------------------------
class PoolEvent:
    """Base for events delivered to the pool's ``on_event`` callback."""

    __slots__ = ("url",)

    def __init__(self, url: str):
        self.url = url

    def __repr__(self) -> str:
        fields = ", ".join(
            f"{name}={getattr(self, name)!r}"
            for cls in type(self).__mro__ for name in getattr(cls, "__slots__", ())
        )
        return f"{type(self).__name__}({fields})"


class EndpointEjected(PoolEvent):
    """Passive outlier ejection fired for ``url``."""

    __slots__ = ("window_s", "consecutive_failures", "ejection_count")

    def __init__(self, url, window_s, consecutive_failures, ejection_count):
        super().__init__(url)
        self.window_s = window_s
        self.consecutive_failures = consecutive_failures
        self.ejection_count = ejection_count


class EndpointQuarantined(PoolEvent):
    """Byzantine-replica quarantine fired for ``url``: ``invalid_count``
    contract-violating responses (resilience's INVALID domain) landed
    inside the quarantine window, so the endpoint is ejected for
    ``window_s`` with the usual exponential backoff. Unlike transport
    ejection this is evidence the replica is WRONG, not slow — the
    doctor's ``byzantine_replica`` anomaly names it from this state."""

    __slots__ = ("window_s", "invalid_count", "quarantine_count")

    def __init__(self, url, window_s, invalid_count, quarantine_count):
        super().__init__(url)
        self.window_s = window_s
        self.invalid_count = invalid_count
        self.quarantine_count = quarantine_count


class EndpointReadmitted(PoolEvent):
    """An ejected endpoint's window expired (or it proved itself healthy)."""

    __slots__ = ()


class EndpointHealthChanged(PoolEvent):
    """The active ready-probe flipped this endpoint's health."""

    __slots__ = ("healthy",)

    def __init__(self, url, healthy: bool):
        super().__init__(url)
        self.healthy = healthy


class SequenceAbandoned(PoolEvent):
    """A non-idempotent (sequence) request failed in flight: the pool did
    NOT re-send it to another replica (the server may already have applied
    its state transition). The application owns re-driving the sequence.
    Delivered to ``on_event``; the original transport error still raises."""

    __slots__ = ("request_id", "sequence_id", "cause")

    def __init__(self, url, request_id: str, sequence_id: int,
                 cause: BaseException):
        super().__init__(url)
        self.request_id = request_id
        self.sequence_id = sequence_id
        self.cause = cause


class RoleFallback(PoolEvent):
    """A role-scoped selection found its role empty, saturated or fully
    unavailable and the caller degraded to role-less (monolithic)
    serving. Emitted by the disaggregated prefill/decode layer through
    ``pool.emit`` — degradation is typed and observable, never a silent
    behavior change. ``url`` is the fallback endpoint that absorbed the
    request ('' when even the fallback selection failed)."""

    __slots__ = ("role", "reason")

    def __init__(self, url: str, role: str, reason: str):
        super().__init__(url)
        self.role = role
        self.reason = reason


class EndpointSpec:
    """One replica address plus its serving role.

    Pass instances in a pool's ``urls`` list to label endpoints for
    role-aware selection (disaggregated prefill/decode serving routes
    prefill and decode to differently-labeled replicas)::

        PoolClient([EndpointSpec("h1:8000", role="prefill"),
                    EndpointSpec("h2:8000", role="decode")])

    Plain strings stay role-less (``role=None``) and behave exactly as
    before; role-less endpoints are eligible for every role-less
    selection and serve as the monolithic fallback tier."""

    __slots__ = ("url", "role")

    def __init__(self, url: str, role: Optional[str] = None):
        if not url or not isinstance(url, str):
            raise ValueError("EndpointSpec needs a non-empty url string")
        if role is not None and (not role or not isinstance(role, str)):
            raise ValueError("role must be a non-empty string (or None)")
        self.url = url
        self.role = role

    def __repr__(self) -> str:
        return f"EndpointSpec({self.url!r}, role={self.role!r})"


class HedgePolicy:
    """When and how to hedge an idempotent infer.

    ``delay_s=None`` (default) uses the pool's rolling p95 of recent infer
    latencies — the canonical "hedge after the tail begins" setting; until
    ``min_latency_samples`` latencies are recorded, ``fallback_delay_s``
    is used. ``jitter_frac`` multiplies the delay by ``1 + U(0, frac)``
    drawn from the injectable ``rng`` (deterministic under a seeded rng)
    so synchronized clients don't hedge in lockstep. ``max_hedges`` bounds
    extra in-flight copies per request (1 = primary + one hedge)."""

    def __init__(
        self,
        delay_s: Optional[float] = None,
        fallback_delay_s: float = 0.05,
        jitter_frac: float = 0.1,
        max_hedges: int = 1,
        min_latency_samples: int = 8,
        rng: Optional[random.Random] = None,
    ):
        if max_hedges < 1:
            raise ValueError("max_hedges must be >= 1")
        self.delay_s = delay_s
        self.fallback_delay_s = fallback_delay_s
        self.jitter_frac = jitter_frac
        self.max_hedges = max_hedges
        self.min_latency_samples = min_latency_samples
        self.rng = rng

    def delay(self, rolling_p95_s: Optional[float],
              rng: Optional[random.Random] = None) -> float:
        base = self.delay_s
        if base is None:
            base = (rolling_p95_s if rolling_p95_s is not None
                    else self.fallback_delay_s)
        r = self.rng or rng
        if self.jitter_frac and r is not None:
            base *= 1.0 + r.uniform(0.0, self.jitter_frac)
        return base


class EndpointState:
    """One replica: its client, breaker-backed policy, and outlier state.

    All mutable fields are guarded by the owning pool's lock.
    ``limiter`` (optional) is a per-endpoint
    :class:`~client_tpu.admission.AdaptiveLimiter`: selection skips an
    endpoint whose outstanding count has reached its adaptive limit, and
    ``shed_total`` counts the requests shed because EVERY candidate was
    at its limit. ``_orca_weight`` is the hysteresis-smoothed
    ``orca_weighted`` routing weight (None until the first fresh load)."""

    __slots__ = (
        "url", "client", "policy", "weight", "role", "outstanding", "healthy",
        "consecutive_failures", "ejected", "ejected_until", "ejection_count",
        "last_ejection_end", "_wrr_current", "limiter", "shed_total",
        "_orca_weight", "affinity_routed", "affinity_rehomed",
        "affinity_spilled", "_affinity_keys",
        "invalid_total", "quarantined", "quarantine_count", "_invalid_times",
    )

    def __init__(self, url: str, client: Any, policy: ResiliencePolicy,
                 weight: float = 1.0, limiter: Optional[AdaptiveLimiter] = None,
                 role: Optional[str] = None):
        self.url = url
        self.client = client
        self.policy = policy  # breaker + per-endpoint ResilienceStats
        self.weight = weight
        self.role = role  # serving role label (None = role-less/monolithic)
        self.outstanding = 0
        self.healthy = True
        self.consecutive_failures = 0
        self.ejected = False
        self.ejected_until = 0.0
        self.ejection_count = 0
        self.last_ejection_end = 0.0
        self._wrr_current = 0.0
        self.limiter = limiter
        self.shed_total = 0
        self._orca_weight: Optional[float] = None
        # affinity routing accounting (disjoint: every pick lands in ONE
        # bucket): picks landed here as the key's home (routed), because
        # the home was ineligible (rehomed), or because the home was over
        # its bounded-load limit (spilled) — plus the capped distinct-key
        # set behind the doctor's affinity_skew flag
        self.affinity_routed = 0
        self.affinity_rehomed = 0
        self.affinity_spilled = 0
        self._affinity_keys: set = set()
        # byzantine-replica accounting: contract-violating (INVALID)
        # responses, the sliding timestamp window behind quarantine, and
        # whether the CURRENT ejection is a quarantine (vs transport)
        self.invalid_total = 0
        self.quarantined = False
        self.quarantine_count = 0
        self._invalid_times: deque = deque()


class EndpointPool:
    """The transport-free engine: selection, health, and outlier ejection.

    Thread-safe; shared by the sync and asyncio pool clients. Events are
    emitted OUTSIDE the internal lock (the callback may call back into
    the pool)."""

    def __init__(
        self,
        endpoints: Sequence[EndpointState],
        routing: str = ROUND_ROBIN,
        eject_after: int = 3,
        base_ejection_s: float = 1.0,
        ejection_multiplier: float = 2.0,
        max_ejection_s: float = 30.0,
        ejection_decay_s: float = 60.0,
        latency_window: int = 256,
        clock: Callable[[], float] = time.monotonic,
        on_event: Optional[Callable[[PoolEvent], None]] = None,
        load_lookup: Optional[Callable[[], Dict[str, Any]]] = None,
        affinity_bound: float = _AFFINITY_BOUND,
        quarantine_after: int = 3,
        quarantine_window_s: float = 30.0,
    ):
        """``load_lookup`` (``orca_weighted`` routing): a zero-arg callable
        returning ``{url: observe.EndpointLoad}`` containing ONLY
        TTL-fresh reports — typically ``Telemetry.endpoint_loads``. A pick
        where any candidate lacks a fresh report falls back to
        least-outstanding: the policy never routes on (or divides by) an
        expired load."""
        if not endpoints:
            raise ValueError("pool needs at least one endpoint")
        if routing not in _ROUTING_POLICIES:
            raise ValueError(
                f"unknown routing policy {routing!r} (one of {_ROUTING_POLICIES})")
        if eject_after < 1:
            raise ValueError("eject_after must be >= 1")
        self.endpoints: List[EndpointState] = list(endpoints)
        self.routing = routing
        self.eject_after = eject_after
        self.base_ejection_s = base_ejection_s
        self.ejection_multiplier = ejection_multiplier
        self.max_ejection_s = max_ejection_s
        self.ejection_decay_s = ejection_decay_s
        # at most ceil(N/2) replicas may ever be ejected at once: the pool
        # must degrade (keep trying suspect replicas) before it self-blinds
        self.max_ejected = math.ceil(len(self.endpoints) / 2)
        # RoleFallback emissions per role (role-aware callers degrading to
        # monolithic serving); read by health_summary/doctor
        self.role_fallbacks: Dict[str, int] = {}
        if affinity_bound < 1.0:
            raise ValueError("affinity_bound must be >= 1.0")
        self.affinity_bound = affinity_bound
        if quarantine_after < 1:
            raise ValueError("quarantine_after must be >= 1")
        # byzantine quarantine: N INVALID (contract-violating) responses
        # inside the sliding window ejects the endpoint (same backoff +
        # max_ejected guard as transport ejection)
        self.quarantine_after = quarantine_after
        self.quarantine_window_s = quarantine_window_s
        self._clock = clock
        self._on_event = on_event
        self._load_lookup = load_lookup
        # micro-cache over the lookup: loads only change on response
        # ingest, so a few ms of reuse spares the per-pick dict build
        # (and the telemetry-lock acquire) on the hot routing path.
        # Real time on purpose — a test-injected fake pool clock must
        # not freeze the cache across ingests.
        self._load_cache: Any = None
        self._load_cache_at = 0.0
        self._lock = threading.Lock()
        self._rr = 0
        self._latencies: deque = deque(maxlen=latency_window)

    # -- events --------------------------------------------------------------
    def emit(self, event: PoolEvent) -> None:
        if isinstance(event, RoleFallback):
            # counted whether or not anyone listens: the doctor's
            # role_degraded anomaly reads this to prove fallback traffic
            # is actually flowing while a role has no healthy member
            with self._lock:
                self.role_fallbacks[event.role] = (
                    self.role_fallbacks.get(event.role, 0) + 1)
        if self._on_event is None:
            return
        try:
            self._on_event(event)
        except Exception:
            pass  # an observer must never break the data path

    def _emit_all(self, events: List[PoolEvent]) -> None:
        for event in events:
            self.emit(event)

    # -- selection -----------------------------------------------------------
    def _readmit_expired(self, now: float, events: List[PoolEvent]) -> None:
        for ep in self.endpoints:
            if ep.ejected and now >= ep.ejected_until:
                ep.ejected = False
                ep.quarantined = False
                ep.consecutive_failures = 0
                events.append(EndpointReadmitted(ep.url))

    @staticmethod
    def _within_limit(ep: EndpointState) -> bool:
        return ep.limiter is None or ep.limiter.would_admit(ep.outstanding)

    def _orca_weights(self,
                      candidates: List[EndpointState]) -> Optional[Dict[int, float]]:
        """Hysteresis-smoothed smooth-WRR weights from the TTL-fresh load
        reports, or None when ANY candidate lacks a fresh report (the
        whole pick then falls back to least-outstanding — a half-fresh
        weighting would starve exactly the replicas whose reports went
        silent). Caller holds the pool lock."""
        lookup = self._load_lookup
        if lookup is None:
            return None
        now = time.monotonic()
        if self._load_cache is not None and now - self._load_cache_at < 0.002:
            loads = self._load_cache
        else:
            try:
                loads = lookup()  # TTL-filtered by the telemetry
            except Exception:
                return None
            self._load_cache = loads
            self._load_cache_at = now
        if not loads:
            return None
        per_ep = []
        for ep in candidates:
            load = loads.get(ep.url)
            if load is None:
                return None  # stale or absent: never route on it
            per_ep.append((ep, load))
        # fleet-relative normalizers for the qps / busy-time signals
        qps_values = [l.metrics.get("rps_fractional", l.metrics.get("qps"))
                      for _, l in per_ep]
        max_qps = max((q for q in qps_values if q is not None), default=None)
        busy_values = [l.metrics.get("named_metrics.avg_compute_infer_us")
                       for _, l in per_ep]
        max_busy = max((b for b in busy_values if b is not None), default=None)
        weights: Dict[int, float] = {}
        for ep, load in per_ep:
            score = load_score(load, max_qps, max_busy)
            if score is None:
                return None  # a report with no usable signal: fall back
            target = max(1.0 - score, _ORCA_WEIGHT_FLOOR) * ep.weight
            old = ep._orca_weight
            if old is None:
                smoothed = target
            elif abs(target - old) < _ORCA_HYSTERESIS * max(old, 1e-9):
                smoothed = old  # inside the hysteresis band: hold steady
            else:
                smoothed = old + _ORCA_SMOOTHING * (target - old)
            ep._orca_weight = smoothed
            weights[id(ep)] = smoothed
        return weights

    def _pick_affinity(self, candidates: List[EndpointState],
                       affinity_key: str) -> EndpointState:
        """Rendezvous-hash the key onto its home endpoint with a
        bounded-load spill: the winner is the highest-scoring ELIGIBLE
        candidate whose outstanding count is under ``affinity_bound``
        times the candidates' fair share — a saturated home sheds the
        overflow to the key's deterministic runner-up instead of queueing
        hot keys behind one drowning replica. Caller holds the pool lock.
        Re-homing is deterministic: every client ranks (key, url)
        identically, so an ejected/unhealthy/breaker-open home moves the
        key to the SAME fallback everywhere, and the key returns home the
        moment the home becomes eligible again."""
        digest = hashlib.blake2b(
            str(affinity_key).encode(), digest_size=8).digest()
        ranked = _affinity_ranked(digest, candidates)
        # the key's TRUE home ranks over the whole pool, eligible or not:
        # the rehomed-vs-spilled split below must know whether the home
        # was missing from the candidate set or merely over its bound
        home = _affinity_ranked(digest, self.endpoints)[0]
        total = sum(ep.outstanding for ep in candidates)
        limit = max(1.0,
                    self.affinity_bound * (total + 1.0) / len(candidates))
        chosen = None
        for ep in ranked:
            if ep.outstanding < limit:
                chosen = ep
                break
        if chosen is None:
            chosen = ranked[0]  # every candidate over the bound: go home
        # disjoint counters: every pick lands in exactly ONE bucket, so
        # routed + rehomed + spilled = total affinity picks
        if chosen is home:
            chosen.affinity_routed += 1
            _flight.note("pool", "affinity", outcome="home", url=chosen.url)
        elif home in candidates:
            chosen.affinity_spilled += 1
            _flight.note("pool", "affinity", outcome="spill",
                         url=chosen.url, home=home.url)
        else:
            chosen.affinity_rehomed += 1
            _flight.note("pool", "affinity", outcome="rehome",
                         url=chosen.url, home=home.url)
        if len(chosen._affinity_keys) < _AFFINITY_KEY_CAP:
            chosen._affinity_keys.add(digest)
        return chosen

    def _pick(self, candidates: List[EndpointState],
              affinity_key: Optional[str] = None) -> EndpointState:
        routing = self.routing
        if routing == AFFINITY:
            if affinity_key is not None:
                # affinity accounting runs even for a lone candidate: the
                # key-spread/rehome counters must reflect every pick
                return self._pick_affinity(candidates, affinity_key)
            # keyless request on an affinity pool: client-local pressure
            routing = LEAST_OUTSTANDING
        if len(candidates) == 1:
            return candidates[0]
        if routing == ORCA_WEIGHTED:
            weights = self._orca_weights(candidates)
            if weights is not None:
                # smooth-WRR over the load-derived weights (same
                # algorithm as the static ``weighted`` policy)
                total = sum(weights.values())
                for ep in candidates:
                    ep._wrr_current += weights[id(ep)]
                best = max(candidates, key=lambda e: e._wrr_current)
                best._wrr_current -= total
                return best
            # loads stale/absent/unusable: degrade to least_outstanding
            # (client-local pressure) rather than stalling or guessing
            routing = LEAST_OUTSTANDING
        if routing == LEAST_OUTSTANDING:
            least = min(ep.outstanding for ep in candidates)
            candidates = [ep for ep in candidates if ep.outstanding == least]
            # ties rotate so idle pools still spread load
        elif routing == WEIGHTED:
            # smooth weighted round-robin (nginx algorithm): deterministic,
            # interleaves instead of bursting onto the heaviest endpoint
            total = sum(ep.weight for ep in candidates)
            for ep in candidates:
                ep._wrr_current += ep.weight
            best = max(candidates, key=lambda e: e._wrr_current)
            best._wrr_current -= total
            return best
        idx = self._rr % len(candidates)
        self._rr += 1
        return candidates[idx]

    def roles(self) -> Dict[Optional[str], int]:
        """Endpoint count per role label (``None`` = role-less)."""
        out: Dict[Optional[str], int] = {}
        with self._lock:
            for ep in self.endpoints:
                out[ep.role] = out.get(ep.role, 0) + 1
        return out

    def select(self, exclude: Sequence[EndpointState] = (),
               affinity_key: Optional[str] = None,
               role: Optional[str] = None) -> EndpointState:
        """Pick an endpoint under the routing policy, honoring health,
        ejection windows, breaker admission and (when armed) each
        endpoint's adaptive concurrency limit. ``affinity_key`` (with
        ``routing="affinity"``) rendezvous-hashes the key onto its home
        endpoint with deterministic bounded-load fallback — see
        :meth:`_pick_affinity`. ``exclude`` lists
        endpoints already tried by this call's failover loop.
        ``role`` restricts the whole selection (healthy AND panic tier)
        to endpoints carrying that role label — the disaggregated
        prefill/decode layer routes each leg this way; a role with no
        members at all raises :class:`NoEndpointAvailableError`
        immediately (the caller owns the typed fallback to role-less
        serving). When no
        eligible endpoint remains, panic-routes to a non-excluded endpoint
        whose breaker would still admit (degraded beats unavailable);
        raises :class:`NoEndpointAvailableError` when even that is empty.
        When the ONLY thing blocking every survivor is its adaptive
        limit, the pool is genuinely saturated — that raises a typed
        :class:`~client_tpu.admission.AdmissionRejected` (reason
        ``endpoint_saturated``, counted per endpoint as ``shed_total``)
        instead of piling more work onto replicas already past their
        limits."""
        events: List[PoolEvent] = []
        excluded = set(map(id, exclude))
        saturated = False
        with self._lock:
            now = self._clock()
            self._readmit_expired(now, events)
            members = (self.endpoints if role is None
                       else [ep for ep in self.endpoints if ep.role == role])
            if role is not None and not members:
                raise NoEndpointAvailableError(
                    f"no endpoint with role {role!r} in the pool")
            # healthy tier first, WITHOUT the limiter: whether the pool
            # enters the panic tier must depend on health/ejection/breaker
            # alone — healthy replicas transiently at their adaptive limit
            # must shed, never spill traffic onto an ejected outlier
            healthy = [
                ep for ep in members
                if id(ep) not in excluded and not ep.ejected and ep.healthy
                and (ep.policy.breaker is None
                     or ep.policy.breaker.would_admit())
            ]
            candidates = [ep for ep in healthy if self._within_limit(ep)]
            if not candidates and healthy:
                # every HEALTHY replica is blocked only by its adaptive
                # limit: the pool is genuinely saturated — shed (typed)
                saturated = True
                for ep in healthy:
                    ep.shed_total += 1
            elif not candidates:
                # panic tier: no healthy replica at all — ignore health/
                # ejection, still skip endpoints whose breaker would
                # fast-fail without touching a socket
                relaxed = [
                    ep for ep in members
                    if id(ep) not in excluded
                    and (ep.policy.breaker is None
                         or ep.policy.breaker.would_admit())
                ]
                candidates = [ep for ep in relaxed if self._within_limit(ep)]
                if not candidates and relaxed:
                    saturated = True
                    for ep in relaxed:
                        ep.shed_total += 1
            picked = (self._pick(candidates, affinity_key)
                      if candidates else None)
        self._emit_all(events)
        if picked is None:
            if saturated:
                raise AdmissionRejected(
                    SHED_ENDPOINT_SATURATED, lane="endpoint",
                    msg="every candidate endpoint is at its adaptive "
                        "concurrency limit")
            raise NoEndpointAvailableError()
        return picked

    def endpoint_by_url(self, url: str) -> EndpointState:
        """The EndpointState serving ``url`` (the sharded scatter-gather
        layer pins each shard to one replica by url). Raises
        :class:`NoEndpointAvailableError` for an unknown url — a layout
        naming a replica outside the pool has no legal target."""
        for ep in self.endpoints:
            if ep.url == url:
                return ep
        raise NoEndpointAvailableError(
            f"endpoint {url!r} is not a member of this pool")

    # -- accounting ----------------------------------------------------------
    def begin(self, ep: EndpointState) -> None:
        with self._lock:
            ep.outstanding += 1

    def done(self, ep: EndpointState) -> None:
        with self._lock:
            ep.outstanding = max(0, ep.outstanding - 1)

    def record_success(self, ep: EndpointState,
                       latency_s: Optional[float] = None) -> None:
        events: List[PoolEvent] = []
        if ep.limiter is not None:
            # latency None (admin/metadata calls) is a neutral feed: the
            # per-endpoint limit tracks INFER latency only
            ep.limiter.on_result(latency_s, ok=True)
        with self._lock:
            ep.consecutive_failures = 0
            if ep.ejected:
                # proved itself (panic routing landed here and succeeded):
                # readmit early rather than waiting out the window — a
                # contract-VALIDATED success even clears quarantine (the
                # replica demonstrably answers correctly again)
                ep.ejected = False
                ep.quarantined = False
                events.append(EndpointReadmitted(ep.url))
            if latency_s is not None:
                self._latencies.append(latency_s)
        self._emit_all(events)

    def record_failure(self, ep: EndpointState, domain: str) -> None:
        """Feed one transport-level failure (connect/transient/timeout —
        FATAL application errors prove delivery and belong in
        :meth:`record_success`) into the outlier detector."""
        if domain not in (CONNECT, TRANSIENT, TIMEOUT):
            return
        if ep.limiter is not None:
            # a transport-level failure is the strongest back-off signal
            # the endpoint can send: decay its adaptive limit
            ep.limiter.on_result(None, ok=False)
        events: List[PoolEvent] = []
        with self._lock:
            ep.consecutive_failures += 1
            if ep.consecutive_failures < self.eject_after or ep.ejected:
                pass
            else:
                now = self._clock()
                already = sum(
                    1 for e in self.endpoints
                    if e.ejected and e.ejected_until > now)
                if already < self.max_ejected:
                    if (ep.last_ejection_end
                            and now - ep.last_ejection_end > self.ejection_decay_s):
                        ep.ejection_count = 0  # long-healthy: forgive history
                    window = min(
                        self.base_ejection_s
                        * (self.ejection_multiplier ** ep.ejection_count),
                        self.max_ejection_s,
                    )
                    ep.ejected = True
                    ep.ejected_until = now + window
                    ep.last_ejection_end = ep.ejected_until
                    ep.ejection_count += 1
                    events.append(EndpointEjected(
                        ep.url, window, ep.consecutive_failures,
                        ep.ejection_count))
        self._emit_all(events)

    def record_invalid(self, ep: EndpointState) -> None:
        """Feed one contract-violating (INVALID) response into the
        byzantine quarantine: the endpoint ANSWERED — so this is neither
        a breaker failure nor transport-outlier evidence — but
        ``quarantine_after`` invalid responses inside
        ``quarantine_window_s`` eject it with the usual exponential
        backoff (and the ``max_ejected`` self-blind guard). Deliberately
        NOT ``record_success``: a wrong answer must never readmit an
        ejected endpoint early."""
        events: List[PoolEvent] = []
        with self._lock:
            now = self._clock()
            ep.invalid_total += 1
            times = ep._invalid_times
            times.append(now)
            cutoff = now - self.quarantine_window_s
            while times and times[0] < cutoff:
                times.popleft()
            if len(times) >= self.quarantine_after and not ep.ejected:
                already = sum(
                    1 for e in self.endpoints
                    if e.ejected and e.ejected_until > now)
                if already < self.max_ejected:
                    if (ep.last_ejection_end
                            and now - ep.last_ejection_end > self.ejection_decay_s):
                        ep.ejection_count = 0  # long-healthy: forgive history
                    window = min(
                        self.base_ejection_s
                        * (self.ejection_multiplier ** ep.ejection_count),
                        self.max_ejection_s,
                    )
                    ep.ejected = True
                    ep.quarantined = True
                    ep.ejected_until = now + window
                    ep.last_ejection_end = ep.ejected_until
                    ep.ejection_count += 1
                    ep.quarantine_count += 1
                    invalid_count = len(times)
                    times.clear()
                    events.append(EndpointQuarantined(
                        ep.url, window, invalid_count, ep.quarantine_count))
                    _flight.note("integrity", "quarantine", url=ep.url,
                                 window_s=window,
                                 quarantine_count=ep.quarantine_count)
        self._emit_all(events)

    def quarantine_dominated(self) -> bool:
        """More than half the endpoints currently sit in quarantine —
        the federation layer treats such a cell as down (a majority of
        demonstrably-lying replicas is worse than a dead cell: spillover
        is strictly safer)."""
        with self._lock:
            now = self._clock()
            quarantined = sum(
                1 for ep in self.endpoints
                if ep.quarantined and ep.ejected and ep.ejected_until > now)
        return quarantined * 2 > len(self.endpoints)

    def set_health(self, ep: EndpointState, healthy: bool) -> None:
        events: List[PoolEvent] = []
        with self._lock:
            if ep.healthy != healthy:
                ep.healthy = healthy
                events.append(EndpointHealthChanged(ep.url, healthy))
        self._emit_all(events)

    # -- introspection -------------------------------------------------------
    def latency_p95(self, min_samples: int = 8) -> Optional[float]:
        with self._lock:
            if len(self._latencies) < min_samples:
                return None
            ordered = sorted(self._latencies)
        return sorted_percentile(ordered, 0.95)

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Per-endpoint state + the per-endpoint ResilienceStats counters."""
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            now = self._clock()
            for i, ep in enumerate(self.endpoints):
                breaker = ep.policy.breaker
                ejected = ep.ejected and ep.ejected_until > now
                key = ep.url if ep.url not in out else f"{ep.url}#{i}"
                out[key] = {
                    "role": ep.role,
                    "healthy": ep.healthy,
                    "ejected": ejected,
                    "ejected_for_s": round(max(0.0, ep.ejected_until - now), 3)
                    if ejected else 0.0,
                    "consecutive_failures": ep.consecutive_failures,
                    "ejection_count": ep.ejection_count,
                    "outstanding": ep.outstanding,
                    "weight": ep.weight,
                    # admission view: the adaptive per-endpoint limit (None
                    # when no limiter is armed), the in-flight count it
                    # gates, and how many requests were shed because every
                    # candidate sat at its limit
                    "limit": (round(ep.limiter.limit, 2)
                              if ep.limiter is not None else None),
                    "inflight": ep.outstanding,
                    "shed_total": ep.shed_total,
                    "breaker_state": breaker.state if breaker is not None else None,
                    "resilience": ep.policy.stats.as_dict(),
                    # byzantine view: contract-violating responses seen,
                    # whether the current ejection is a quarantine, and
                    # how many quarantines this endpoint has earned
                    "invalid_total": ep.invalid_total,
                    "quarantined": ep.quarantined and ejected,
                    "quarantine_count": ep.quarantine_count,
                }
                if self.routing == AFFINITY:
                    # affinity view: how many picks landed here and why,
                    # plus the (capped) distinct-key ownership count the
                    # doctor's affinity_skew anomaly reads
                    out[key]["affinity"] = {
                        "routed": ep.affinity_routed,
                        "rehomed": ep.affinity_rehomed,
                        "spilled": ep.affinity_spilled,
                        "keys": len(ep._affinity_keys),
                    }
        return out

    def watch_gauges(self) -> Dict[str, Any]:
        """The watchtower's gauge-source contract: flat pressure gauges
        plus the endpoint NAMES behind them, so a watermark alert can say
        *which* replica is quarantined, not just how many."""
        snap = self.snapshot()
        breaker_open_urls: List[str] = []
        quarantined_urls: List[str] = []
        unrouteable = 0
        for key, stats in snap.items():
            url = key.partition("#")[0]
            if stats.get("breaker_state") == "open":
                breaker_open_urls.append(url)
            if stats.get("quarantined"):
                quarantined_urls.append(url)
            if not (stats["healthy"] and not stats["ejected"]
                    and stats.get("breaker_state") != "open"):
                unrouteable += 1
        return {
            "endpoints": len(snap),
            "breakers_open": len(breaker_open_urls),
            "breaker_open_urls": sorted(set(breaker_open_urls)),
            "quarantined": len(quarantined_urls),
            "quarantined_urls": sorted(set(quarantined_urls)),
            "unrouteable": unrouteable,
        }


# the shared positional-prefix folder lives in _base (the batching
# dispatcher folds the same prefix); legacy aliases kept for callers
_INFER_POSITIONALS = INFER_POSITIONAL_PREFIX
_fold_infer_args = fold_infer_args


def _default_client_factory(protocol: str, aio: bool):
    if protocol == "http":
        if aio:
            import client_tpu.http.aio as mod
        else:
            import client_tpu.http as mod
    elif protocol == "grpc":
        if aio:
            import client_tpu.grpc.aio as mod
        else:
            import client_tpu.grpc as mod
    else:
        raise ValueError(f"unknown protocol {protocol!r} (http|grpc)")
    return mod.InferenceServerClient


def _arena_event_observer(arena, chain=None):
    """Chainable pool observer invalidating the arena's cached shm
    registrations on BOTH edges of a replica's availability: ejection or
    an unhealthy probe (it may be about to restart), AND readmission or
    a healthy-again probe — a replica that healed may have restarted
    DURING the outage, so a re-prefill (or any re-homed request) landing
    on the newly-healed endpoint must re-verify its registration instead
    of trusting the pre-outage cache entry."""

    def observer(event: PoolEvent) -> None:
        if isinstance(
                event, (EndpointEjected, EndpointReadmitted,
                        EndpointHealthChanged)):
            try:
                arena.invalidate_endpoint(event.url)
            except Exception:
                pass  # an observer must never break the data path
        if chain is not None:
            chain(event)

    return observer


class _PoolClientBase:
    """Construction + bookkeeping shared by the sync and asyncio wrappers."""

    _AIO = False

    def __init__(
        self,
        urls: Sequence[str],
        protocol: str = "http",
        client_factory: Optional[Callable[[str], Any]] = None,
        routing: str = ROUND_ROBIN,
        weights: Optional[Sequence[float]] = None,
        health_interval_s: Optional[float] = 1.0,
        probe_timeout_s: float = 1.0,
        eject_after: int = 3,
        base_ejection_s: float = 1.0,
        ejection_multiplier: float = 2.0,
        max_ejection_s: float = 30.0,
        ejection_decay_s: float = 60.0,
        quarantine_after: int = 3,
        quarantine_window_s: float = 30.0,
        breaker_factory: Optional[Callable[[], Optional[CircuitBreaker]]] = None,
        endpoint_retry: Optional[RetryPolicy] = None,
        max_failover_attempts: Optional[int] = None,
        default_deadline_s: Optional[float] = None,
        per_attempt_timeout_s: Optional[float] = None,
        hedge: Optional[HedgePolicy] = None,
        hedge_executor_workers: Optional[int] = None,
        rng: Optional[random.Random] = None,
        on_event: Optional[Callable[[PoolEvent], None]] = None,
        clock: Callable[[], float] = time.monotonic,
        telemetry=None,
        shm_arena=None,
        admission=None,
        endpoint_limits=None,
        affinity_bound: float = _AFFINITY_BOUND,
        seq_pin_idle_s: Optional[float] = 300.0,
    ):
        """``urls``: N ``host:port`` replica addresses. ``client_factory``
        overrides the per-endpoint client constructor (receives the url);
        default builds the ``protocol`` frontend (sync or aio to match this
        wrapper). ``weights`` pairs with ``routing="weighted"``.
        ``endpoint_retry`` arms in-endpoint retries BEFORE failover kicks
        in (default None: failover across replicas IS the retry).
        ``hedge``: a :class:`HedgePolicy` (idempotent infers only); on the
        sync client every hedged attempt (primary included) runs on a
        shared thread pool, so size ``hedge_executor_workers`` to at least
        ``caller_threads * (1 + max_hedges)`` when driving the pool from
        many threads (default: ``max(8, 4 * N)``).
        ``health_interval_s=None`` disables the active prober.
        ``telemetry``: an ``observe.Telemetry`` shared by the pool and every
        endpoint client — pool events feed its counters (ejections,
        readmissions, health flips, hedge win/loss), per-endpoint breakers
        and retries report through it, endpoint stats surface as gauges at
        scrape time, and each endpoint client traces request phases.

        ``admission``: an :class:`~client_tpu.admission.AdmissionController`
        (or ``True`` for defaults) gating every pooled ``infer`` /
        ``generate_stream``: ONE token covers the whole failover/hedge
        engine run; saturated or deadline-infeasible requests raise the
        typed ``AdmissionRejected`` instead of queueing. ``endpoint_limits``
        (``True`` or a zero-arg ``AdaptiveLimiter`` factory) arms a
        per-endpoint adaptive concurrency limit that selection honors
        like a breaker. ``routing="orca_weighted"`` requires ``telemetry``
        (ideally with ``orca_format=`` set so the frontends opt in): the
        smooth-WRR weights come from the TTL-fresh ORCA load reports,
        falling back to least-outstanding whenever any replica's load is
        stale or absent.

        ``routing="affinity"`` rendezvous-hashes a caller-supplied
        ``infer(..., affinity_key=...)`` / ``generate_stream(...,
        affinity_key=...)`` session/prefix key onto a home endpoint with
        deterministic bounded-load fallback (``affinity_bound`` times the
        fair share) — replica-local state (KV caches, session prefixes)
        keeps landing on one replica, survives that replica's ejection by
        re-homing deterministically, and returns home on recovery.
        Keyless requests on an affinity pool route least-outstanding.

        ``seq_pin_idle_s``: sequence pins whose sequence went idle this
        long without a ``sequence_end`` are garbage-collected (the pin is
        dropped and the existing ``SequenceAbandoned`` event fires) — a
        caller that died mid-sequence must not leak its pin forever.
        ``None`` disables the GC."""
        # ``urls`` entries may be plain strings (role-less) or
        # EndpointSpec instances carrying a serving-role label for
        # role-aware selection (disaggregated prefill/decode)
        specs = [u if isinstance(u, EndpointSpec) else EndpointSpec(u)
                 for u in urls]
        urls = [s.url for s in specs]
        if not urls:
            raise ValueError("pool needs at least one url")
        if routing not in _ROUTING_POLICIES:
            raise ValueError(
                f"unknown routing policy {routing!r} (one of {_ROUTING_POLICIES})")
        if weights is not None and len(weights) != len(urls):
            raise ValueError("weights must pair 1:1 with urls")
        if seq_pin_idle_s is not None and seq_pin_idle_s <= 0:
            raise ValueError(
                "seq_pin_idle_s must be > 0 (None disables the pin GC)")
        if weights is None:
            weights = [1.0] * len(urls)
        if client_factory is None:
            client_factory = _default_client_factory(protocol, self._AIO)
        if breaker_factory is None:
            breaker_factory = CircuitBreaker
        if routing == ORCA_WEIGHTED and telemetry is None:
            raise ValueError(
                "routing='orca_weighted' needs telemetry=: the ORCA load "
                "reports it routes on are ingested by observe.Telemetry "
                "(set orca_format='json'|'text' on it so every frontend "
                "opts in to the endpoint-load-metrics header)")
        self._telemetry = telemetry
        if admission is True:
            admission = AdmissionController()
        elif isinstance(admission, dict):
            # kwargs form, so layers that build one pool per cell
            # (federation's pool_kwargs) can arm per-pool controllers —
            # sharing one instance would merge queues across cells
            admission = AdmissionController(**admission)
        self._admission = admission
        if endpoint_limits is True:
            endpoint_limits = AdaptiveLimiter
        limiter_factory = endpoint_limits if callable(endpoint_limits) else None
        if shm_arena is True:
            from .arena import default_arena

            shm_arena = default_arena()
        self._shm_arena = shm_arena
        if shm_arena is not None:
            # ejection means the replica was failing (it may have restarted
            # and lost its server-side shm registrations): drop the arena's
            # cached registrations for that url so the next use re-issues
            # the RPC instead of pointing the server at a region it no
            # longer holds
            on_event = _arena_event_observer(shm_arena, chain=on_event)
        if telemetry is not None:
            # count every typed pool event exactly once, then forward to
            # the caller's observer (if any)
            on_event = telemetry.pool_observer(chain=on_event)
        endpoints: List[EndpointState] = []
        try:
            for spec, weight in zip(specs, weights):
                url = spec.url
                policy = ResiliencePolicy(
                    retry=endpoint_retry, breaker=breaker_factory())
                if telemetry is not None:
                    telemetry.attach(policy)  # retries/fast-fails/breaker
                client = client_factory(url)
                # every call through this client now runs under the
                # endpoint's breaker and is counted in its stats
                client.configure_resilience(policy)
                if telemetry is not None and hasattr(
                        client, "configure_telemetry"):
                    client.configure_telemetry(telemetry)
                if shm_arena is not None and hasattr(
                        client, "configure_arena"):
                    # each endpoint client carries the SAME arena: one slab
                    # write serves every replica, and registrations cache
                    # per (endpoint url, region)
                    client.configure_arena(shm_arena)
                endpoints.append(EndpointState(
                    url, client, policy, weight,
                    limiter=limiter_factory() if limiter_factory else None,
                    role=spec.role))
        except Exception:
            self._abandon(endpoints)
            raise
        try:
            self.pool = EndpointPool(
                endpoints,
                routing=routing,
                eject_after=eject_after,
                base_ejection_s=base_ejection_s,
                ejection_multiplier=ejection_multiplier,
                max_ejection_s=max_ejection_s,
                ejection_decay_s=ejection_decay_s,
                quarantine_after=quarantine_after,
                quarantine_window_s=quarantine_window_s,
                clock=clock,
                on_event=on_event,
                # orca_weighted: weights come from the telemetry's
                # TTL-filtered load map — an expired report is simply
                # absent, so the policy can never divide by a stale load
                load_lookup=(telemetry.endpoint_loads
                             if routing == ORCA_WEIGHTED else None),
                affinity_bound=affinity_bound,
            )
        except Exception:
            self._abandon(endpoints)
            raise
        if telemetry is not None:
            # per-endpoint health/ejection/breaker/outstanding gauges,
            # refreshed from pool.snapshot() at scrape time
            telemetry.register_pool(self.pool)
            if self._admission is not None:
                # shed/admit counters + limit/inflight/queue-depth gauges
                telemetry.attach_admission(self._admission)
                if getattr(self._admission, "tenancy", None) is not None:
                    # per-tenant admitted/shed/quota/burn gauges
                    self._admission.tenancy.attach_telemetry(telemetry)
        self._hedge = hedge
        self._hedge_executor_workers = (
            hedge_executor_workers
            if hedge_executor_workers is not None
            else max(8, 4 * len(urls)))
        self._rng = rng or random.Random()
        self._health_interval_s = health_interval_s or None
        self._probe_timeout_s = probe_timeout_s
        self._max_failover_attempts = max_failover_attempts or len(urls)
        if default_deadline_s is not None or per_attempt_timeout_s is not None:
            self._budget_policy: Optional[ResiliencePolicy] = ResiliencePolicy(
                retry=RetryPolicy(
                    max_attempts=1,
                    total_deadline_s=default_deadline_s,
                    per_attempt_timeout_s=per_attempt_timeout_s,
                ))
        else:
            self._budget_policy = None
        # sequence affinity: server-side sequence state (KV caches, CORRID
        # slots) is replica-local, so every request of one sequence must
        # land on the SAME endpoint; pins live until sequence_end (or until
        # the sequence is abandoned). "established" = at least one request
        # of the sequence reached the pinned replica.
        self._seq_lock = threading.Lock()
        self._seq_pins: Dict[int, EndpointState] = {}
        self._seq_established: set = set()
        # pin GC: a caller that dies without sequence_end must not leak
        # its pin — pins idle past seq_pin_idle_s are swept (emitting
        # SequenceAbandoned) on the sequence path and the prober cadence
        self._clock = clock
        self._seq_pin_idle_s = seq_pin_idle_s
        self._seq_gc_interval_s = (
            max(seq_pin_idle_s / 4.0, 0.01)
            if seq_pin_idle_s is not None else None)
        self._seq_last_used: Dict[int, float] = {}
        self._seq_gc_at = clock()
        # backoff schedule for re-attempting a PINNED replica (a sequence
        # has exactly one legal endpoint, so zero-delay retries would burn
        # every attempt inside a sub-second connect blip)
        self._seq_backoff_policy = RetryPolicy(
            initial_backoff_s=0.05, max_backoff_s=0.5, rng=self._rng)
        self._closed = False

    @staticmethod
    def _abandon(endpoints: List[EndpointState]) -> None:
        for ep in endpoints:
            try:
                close = ep.client.close
            except AttributeError:
                continue
            try:
                result = close()
                if hasattr(result, "close"):  # unawaited coroutine
                    result.close()
            except Exception:
                pass

    # method-name prefixes whose calls mutate SERVER-side (or client-side)
    # state: these broadcast to every endpoint — registering a shm region
    # or loading a model on one arbitrary replica while infers route to
    # all of them would be a trap
    _BROADCAST_PREFIXES = (
        "register_", "unregister_", "load_model", "unload_model", "update_",
    )

    def configure_resilience(self, policy):
        raise InferenceServerException(
            "PoolClient owns each endpoint's resilience policy (breaker + "
            "stats); configure endpoint_retry= / breaker_factory= at pool "
            "construction instead")

    def configure_telemetry(self, telemetry):
        raise InferenceServerException(
            "PoolClient wires telemetry through every endpoint at "
            "construction; pass telemetry= to the pool constructor instead")

    def telemetry(self):
        return self._telemetry

    def configure_arena(self, arena):
        raise InferenceServerException(
            "PoolClient wires the shm arena through every endpoint (and its "
            "ejection-invalidation hook) at construction; pass shm_arena= "
            "to the pool constructor instead")

    def arena(self):
        return self._shm_arena

    def admission(self):
        return self._admission

    # -- admission helpers ---------------------------------------------------
    def _admission_deadline(self, timeout_s: Optional[float]) -> Optional[float]:
        """The request's absolute deadline under the pool's budget policy
        (the caller's explicit timeout wins) — what deadline-aware
        shedding judges feasibility against."""
        return AttemptBudget(self._budget_policy, timeout_s).deadline

    def _admission_note_shed(self, exc: AdmissionRejected) -> None:
        """Export a shed raised below the controller (the per-endpoint
        saturation path) exactly once; controller-level sheds were
        already counted by its observer."""
        if exc.counted:
            return
        exc.counted = True
        tel = self._telemetry
        if tel is not None:
            try:
                tel.on_admission_shed(exc.lane, exc.reason)
            except Exception:
                pass  # an observer must never break the data path

    def _admission_settle(self, token, t0: float,
                          exc: Optional[BaseException]) -> None:
        """Release the pool-level admission slot, feeding the limiter the
        whole pooled call's outcome: successes and FATAL application
        answers are completions (the fleet served them); transport-class
        failures are breaches (the overload back-off signal); sheds,
        breaker fast-fails and interrupts teach nothing."""
        # the call may have finished without any endpoint span claiming
        # the stashed wait (all-ejected select, endpoint saturation, an
        # endpoint client built without configure_telemetry): drop any
        # unclaimed stash or it would leak onto the next, unrelated
        # request's span — a no-op in the common claimed case
        consume_admission_phase()
        if exc is None:
            token.release(time.monotonic() - t0, ok=True)
            return
        if isinstance(exc, AdmissionRejected):
            self._admission_note_shed(exc)
            token.release()
            return
        if isinstance(exc, CircuitOpenError) or not isinstance(exc, Exception):
            token.release()
            return
        if classify_fault(exc) in (CONNECT, TRANSIENT, TIMEOUT):
            token.release(time.monotonic() - t0, ok=False)
        else:
            token.release(time.monotonic() - t0, ok=True)

    @property
    def _FRONTEND(self) -> str:
        """The wrapped protocol's telemetry label (wrapper layers — the
        batching dispatcher — derive their own label from it)."""
        return getattr(
            self.pool.endpoints[0].client, "_FRONTEND", "client")

    def coalescing(self, **kwargs):
        """Wrap this pool in the opt-in coalescing dispatcher
        (``client_tpu.batch``): concurrent compatible ``infer()`` calls
        merge into ONE pooled request — one routing decision, one
        failover/hedge engine run — and the result rows scatter back per
        caller. The pool's telemetry is adopted automatically."""
        from .batch import AioBatchingClient, BatchingClient

        cls = AioBatchingClient if self._AIO else BatchingClient
        return cls(self, **kwargs)

    def caching(self, **kwargs):
        """Wrap this pool in the opt-in singleflight + response-cache
        layer (``client_tpu.cache``): hot content keys are served
        client-side (zero wire requests), concurrent identical misses
        collapse onto one pooled request — one routing decision, one
        admission token — and ``load_model``/``unload_model`` broadcasts
        invalidate the model's cached entries. The pool's telemetry is
        adopted automatically. Compose OUTSIDE ``.coalescing()``."""
        from .cache import AioCachingClient, CachingClient

        cls = AioCachingClient if self._AIO else CachingClient
        return cls(self, **kwargs)

    @classmethod
    def _is_broadcast(cls, name: str) -> bool:
        return any(name.startswith(p) for p in cls._BROADCAST_PREFIXES)

    # -- shared helpers ------------------------------------------------------
    def health_summary(self) -> Dict[str, Any]:
        """The CELL-level aggregate over :meth:`endpoint_stats`: how many
        replicas this pool can actually route to right now, and the
        pressure counters a federation layer (or the doctor's ``--cells``
        snapshot) judges the whole cell by. ``available`` is the binary
        verdict: at least one replica is healthy, un-ejected and not
        breaker-open."""
        snap = self.pool.snapshot()
        healthy = ejected = breaker_open = quarantined = 0
        outstanding = shed_total = invalid_total = 0
        roles: Dict[str, Dict[str, Any]] = {}
        for stats in snap.values():
            if stats["ejected"]:
                ejected += 1
            if stats.get("quarantined"):
                quarantined += 1
            invalid_total += stats.get("invalid_total", 0)
            state = stats.get("breaker_state")
            # only a fully-open breaker is unroutable: half_open is MID
            # RECOVERY and actively admitting probes — counting it down
            # would raise a false whole-cell outage alarm exactly while
            # the cell is healing
            open_breaker = state == "open"
            if open_breaker:
                breaker_open += 1
            routable = (stats["healthy"] and not stats["ejected"]
                        and not open_breaker)
            if routable:
                healthy += 1
            outstanding += stats["outstanding"]
            shed_total += stats.get("shed_total", 0)
            role = stats.get("role")
            if role is not None:
                r = roles.setdefault(
                    role, {"endpoints": 0, "healthy": 0, "available": False})
                r["endpoints"] += 1
                if routable:
                    r["healthy"] += 1
                    r["available"] = True
        out = {
            "endpoints": len(snap),
            "healthy": healthy,
            "ejected": ejected,
            "breaker_open": breaker_open,
            "outstanding": outstanding,
            "shed_total": shed_total,
            "available": healthy > 0,
            # byzantine view: endpoints currently in quarantine + the
            # cell-wide count of contract-violating responses; a
            # quarantine-dominated cell is treated as down by federation
            "quarantined": quarantined,
            "invalid_total": invalid_total,
            "quarantine_dominated": quarantined * 2 > len(snap),
        }
        if roles:
            # per-role availability (disaggregated prefill/decode): a
            # role with zero routable members is the doctor's
            # ``role_degraded`` trigger when fallback traffic flows —
            # ``fallbacks`` counts the RoleFallback events that prove it
            with self.pool._lock:
                for role, r in roles.items():
                    r["fallbacks"] = self.pool.role_fallbacks.get(role, 0)
            out["roles"] = roles
        return out

    def endpoint_stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-endpoint snapshot: health, ejection, breaker state,
        outstanding count, the endpoint's ResilienceStats counters — and,
        when the pool's telemetry has ingested ORCA reports, the latest
        un-expired ``EndpointLoad`` per endpoint (a ``load`` key;
        ``routing="orca_weighted"`` routes on exactly these reports) —
        plus the admission view: the adaptive per-endpoint ``limit``,
        the ``inflight`` count it gates, and ``shed_total``."""
        out = self.pool.snapshot()
        tel = self._telemetry
        if tel is not None:
            loads = tel.endpoint_loads()
            if loads:
                for key, stats in out.items():
                    load = loads.get(key.partition("#")[0])
                    if load is not None:
                        stats["load"] = load.as_dict()
        return out

    def watch_gauges(self) -> Dict[str, Any]:
        """The watchtower's gauge-source contract (delegates to the
        :class:`EndpointPool`, which is what telemetry registers)."""
        return self.pool.watch_gauges()

    def _record_attempt_failure(self, ep: EndpointState,
                                exc: BaseException) -> str:
        """Feed one failed attempt into the outlier detector; returns the
        fault domain ('' for a fast-fail that never touched the wire)."""
        if isinstance(exc, CircuitOpenError):
            return ""  # nothing was sent; the breaker already knows
        domain = classify_fault(exc)
        if domain == INVALID:
            # the endpoint answered WRONG: not record_success (a wrong
            # answer must never readmit an ejected endpoint early), not
            # transport-outlier evidence — quarantine accounting
            self.pool.record_invalid(ep)
        elif domain == FATAL:
            # an application error proves the transport delivered the
            # request — for ejection purposes that is a success
            self.pool.record_success(ep)
        else:
            self.pool.record_failure(ep, domain)
        return domain

    def _sequence_event(self, ep: EndpointState, request_id: str,
                        sequence_id: int, exc: BaseException) -> None:
        _flight.note("pool", "sequence_abandoned", url=ep.url,
                     sequence_id=sequence_id)
        self.pool.emit(SequenceAbandoned(ep.url, request_id, sequence_id, exc))

    # -- sequence affinity helpers -------------------------------------------
    def _seq_gc(self) -> None:
        """Sweep pins whose sequence went idle past ``seq_pin_idle_s``
        without a ``sequence_end`` (the caller died, or simply leaked):
        the pin and its established mark are dropped and the existing
        :class:`SequenceAbandoned` event fires per evicted pin. Without
        this, ``_seq_pins``/``_seq_established`` grow unbounded under
        caller churn. Events are emitted OUTSIDE ``_seq_lock``."""
        if self._seq_pin_idle_s is None:
            return
        now = self._clock()
        evicted: List[Tuple[int, EndpointState]] = []
        with self._seq_lock:
            if now - self._seq_gc_at < self._seq_gc_interval_s:
                return
            self._seq_gc_at = now
            cutoff = now - self._seq_pin_idle_s
            for sid in [sid for sid, ts in self._seq_last_used.items()
                        if ts < cutoff]:
                self._seq_last_used.pop(sid, None)
                self._seq_established.discard(sid)
                ep = self._seq_pins.pop(sid, None)
                if ep is not None:
                    evicted.append((sid, ep))
        for sid, ep in evicted:
            self.pool.emit(SequenceAbandoned(
                ep.url, "", sid, InferenceServerException(
                    f"sequence pin idle for > {self._seq_pin_idle_s:g}s "
                    "with no sequence_end: pin garbage-collected (the "
                    "server-side sequence state is abandoned)",
                    status="SEQUENCE_PIN_EXPIRED")))

    def _seq_endpoint(self, sequence_id: int,
                      exclude: Sequence[EndpointState] = (),
                      affinity_key: Optional[str] = None) -> EndpointState:
        now = self._clock()
        with self._seq_lock:
            # refresh BEFORE the sweep: an idle-then-resumed sequence must
            # never be garbage-collected by its own resuming call
            self._seq_last_used[sequence_id] = now
        self._seq_gc()
        with self._seq_lock:
            ep = self._seq_pins.get(sequence_id)
        if ep is not None:
            return ep
        # select OUTSIDE _seq_lock: selection emits pool events whose
        # callbacks may re-enter the sequence path (non-reentrant lock).
        # An affinity pool places the initial pin by the caller's key, so
        # a resumed session lands back on the replica holding its state.
        candidate = self.pool.select(exclude=exclude,
                                     affinity_key=affinity_key)
        with self._seq_lock:
            return self._seq_pins.setdefault(sequence_id, candidate)

    def _seq_backoff_s(self, attempt: int, budget: AttemptBudget) -> float:
        """Backoff before re-attempting the PINNED replica: the shared
        RetryPolicy full-jitter schedule (seeded-rng deterministic),
        clamped to the remaining budget."""
        delay = self._seq_backoff_policy.backoff_s(attempt)
        if budget.deadline is not None:
            delay = min(delay, max(0.0, budget.deadline - time.monotonic()))
        return delay

    def _seq_mark_established(self, sequence_id: int) -> None:
        with self._seq_lock:
            self._seq_established.add(sequence_id)

    def _seq_unpin(self, sequence_id: int) -> None:
        with self._seq_lock:
            self._seq_pins.pop(sequence_id, None)
            self._seq_established.discard(sequence_id)
            self._seq_last_used.pop(sequence_id, None)

    def _seq_repin_allowed(self, sequence_id: int) -> bool:
        """A connect failure provably never reached the server: if NO
        request of this sequence has landed yet, there is no replica-local
        state and the pin may move; once established, the pin is fixed."""
        with self._seq_lock:
            return sequence_id not in self._seq_established


class PoolClient(_PoolClientBase):
    """Synchronous pool wrapper over the HTTP or GRPC sync frontend.

    Exposes the full ``InferenceServerClient`` surface: ``infer`` runs the
    failover/hedging engine; every other client method is delegated to a
    selected endpoint under the same failover loop (admin/health calls are
    idempotent by nature)."""

    _AIO = False

    def __init__(self, urls, **kwargs):
        super().__init__(urls, **kwargs)
        self._executor_lock = threading.Lock()
        self._executor: Optional[ThreadPoolExecutor] = None
        self._stream_lock = threading.Lock()
        self._stream_ep: Optional[EndpointState] = None
        self._probe_stop = threading.Event()
        self._probe_threads: List[threading.Thread] = []
        if self._health_interval_s:
            # one persistent thread per endpoint: concurrent (a blackholed
            # endpoint never delays another's probe) with no per-tick
            # thread churn
            self._probe_threads = [
                threading.Thread(
                    target=self._probe_loop, args=(ep,),
                    name=f"client_tpu_pool_probe_{i}", daemon=True)
                for i, ep in enumerate(self.pool.endpoints)
            ]
            for t in self._probe_threads:
                t.start()

    # -- lifecycle -----------------------------------------------------------
    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._probe_stop.set()
        for t in self._probe_threads:
            t.join(timeout=self._probe_timeout_s + 5)
        with self._executor_lock:
            if self._executor is not None:
                self._executor.shutdown(wait=False)
                self._executor = None
        for ep in self.pool.endpoints:
            try:
                ep.client.close()
            except Exception:
                pass

    def __enter__(self) -> "PoolClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- active health probing ----------------------------------------------
    def _probe_one(self, ep: EndpointState) -> None:
        try:
            ok = ep.client.is_server_ready(
                probe=True, client_timeout=self._probe_timeout_s)
        except Exception:
            ok = False  # FATAL probe answer: endpoint is up but broken
        self.pool.set_health(ep, ok)

    def _probe_loop(self, ep: EndpointState) -> None:
        while not self._probe_stop.wait(self._health_interval_s):
            self._probe_one(ep)
            # the prober cadence doubles as the idle-pin sweep: a pool
            # with no further sequence traffic must still GC leaked pins
            self._seq_gc()

    def wait_healthy(self, min_healthy: Optional[int] = None,
                     timeout_s: float = 10.0) -> bool:
        """Block until at least ``min_healthy`` endpoints (default: all)
        are healthy, probing directly rather than waiting for the prober
        cadence. Returns False on timeout. Replay/capacity harnesses call
        this before measuring so probe warmup (first requests 503ing or
        routing to not-yet-probed replicas) never pollutes the first
        measurement window."""
        want = len(self.pool.endpoints) if min_healthy is None else min_healthy
        deadline = time.monotonic() + timeout_s
        first_pass = True
        while True:
            healthy = 0
            for ep in self.pool.endpoints:
                # endpoints START optimistically healthy — the first pass
                # must probe every one of them or a down replica would be
                # vouched for without a single probe ever going out
                if first_pass or not ep.healthy:
                    self._probe_one(ep)
                if ep.healthy:
                    healthy += 1
            first_pass = False
            if healthy >= want:
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.05)

    # -- failover engine ------------------------------------------------------
    def _execute(self, op, idempotent: bool = True,
                 timeout_s: Optional[float] = None,
                 request_id: str = "", sequence_id: int = 0,
                 record_latency: bool = False,
                 affinity_key: Optional[str] = None):
        """Run ``op(client, remaining_timeout)`` against the pool: one
        shared deadline budget, at most ``max_failover_attempts`` distinct
        replicas, idempotency-gated re-sends. ``record_latency`` feeds the
        hedge-delay p95 window — infers only, so fast admin/metadata calls
        don't drag the window down and trigger spurious hedges.
        ``affinity_key`` steers every selection (the failover re-select
        excludes the failed home, so the key re-homes deterministically
        instead of retrying a dead replica)."""
        budget = AttemptBudget(self._budget_policy, timeout_s)
        tried: List[EndpointState] = []
        last: Optional[BaseException] = None
        while len(tried) < self._max_failover_attempts:
            try:
                remaining = budget.attempt_timeout_s()
            except InferenceServerException as deadline_exc:
                if last is not None:
                    raise deadline_exc from last
                raise
            try:
                ep = self.pool.select(exclude=tried,
                                      affinity_key=affinity_key)
            except NoEndpointAvailableError:
                if last is not None:
                    raise last
                raise
            tried.append(ep)
            _flight.note("pool", "route", url=ep.url, attempt=len(tried))
            self.pool.begin(ep)
            t0 = time.monotonic()
            try:
                result = op(ep.client, remaining)
            except CircuitOpenError as e:
                last = e  # raced an opening breaker; nothing was sent
                _flight.note("pool", "failover", url=ep.url,
                             domain="circuit_open")
                continue
            except Exception as e:
                domain = self._record_attempt_failure(ep, e)
                if domain == INVALID:
                    # the endpoint answered WRONG (IntegrityError): never
                    # retried on the SAME endpoint — an idempotent request
                    # fails over to a different replica, a sequence
                    # request raises (its state lives on a liar)
                    last = e
                    if not idempotent:
                        self._sequence_event(ep, request_id, sequence_id, e)
                        raise
                    _flight.note("pool", "failover", url=ep.url,
                                 domain=domain)
                    continue
                if domain in (FATAL, SHED):
                    # FATAL: the server answered; SHED: a client-local
                    # admission rejection — failover cannot help either
                    raise
                last = e
                if domain in (TRANSIENT, TIMEOUT) and not idempotent:
                    self._sequence_event(ep, request_id, sequence_id, e)
                    raise
                _flight.note("pool", "failover", url=ep.url, domain=domain)
                continue
            finally:
                self.pool.done(ep)
            self.pool.record_success(
                ep, time.monotonic() - t0 if record_latency else None)
            return result
        assert last is not None
        raise last

    # -- admission gate -------------------------------------------------------
    def _admission_begin(self, kwargs, sequence_id: int,
                         tenant: Optional[str] = None):
        """Acquire the pool-level admission slot (or raise the typed
        ``AdmissionRejected``). Established sequences force-admit:
        shedding a step of server-held sequence state would poison it.
        A non-zero queue wait is stashed for the endpoint client's span
        (the ``admission_queue`` phase)."""
        ctrl = self._admission
        force = bool(sequence_id) and not self._seq_repin_allowed(sequence_id)
        deadline = self._admission_deadline(kwargs.get("client_timeout"))
        t0_ns = time.perf_counter_ns()
        token = ctrl.acquire(
            kwargs.get("priority") or 0, deadline, force=force,
            tenant=tenant)
        if token.waited_s and self._telemetry is not None:
            # only worth stashing when a span can claim it; an unclaimed
            # stash would sit in the contextvar waiting to pollute some
            # unrelated client's next span on this thread
            stash_admission_phase(t0_ns, time.perf_counter_ns())
        return token

    # -- inference -------------------------------------------------------------
    def infer(self, model_name: str, inputs, *args, **kwargs):
        """Pool-routed ``infer`` (positional arguments follow the
        frontends' shared prefix). Sequence requests (``sequence_id != 0``)
        PIN to one endpoint — replica-local sequence state must not
        scatter — are NEVER hedged, re-attempt only never-sent connect
        failures (moving the pin only while the sequence has no
        server-side state yet), and an in-flight death surfaces a
        :class:`SequenceAbandoned` event plus the original error.
        With admission armed, ONE token covers the whole failover/hedge
        engine run; a saturated pool raises ``AdmissionRejected``.
        ``affinity_key=`` (with ``routing="affinity"``) pins the request
        to the key's home endpoint — never forwarded to the replica."""
        kwargs = _fold_infer_args(args, kwargs)
        scratch = _flight.layer_begin(self._telemetry, "pool", model_name)
        if scratch is None:
            return self._infer_gated(model_name, inputs, kwargs)
        try:
            result = self._infer_gated(model_name, inputs, kwargs)
        except BaseException as e:
            _flight.layer_commit(self._telemetry, scratch, error=e)
            raise
        _flight.layer_commit(self._telemetry, scratch)
        return result

    def _infer_gated(self, model_name: str, inputs, kwargs):
        """The admission-gated engine behind :meth:`infer` (split out so
        the flight-recorder wrapper above owns exactly one scratch per
        logical pool request, sheds included)."""
        affinity_key = kwargs.pop("affinity_key", None)
        # the tenant is a CLIENT-side QoS dimension (like affinity_key):
        # popped here so it never reaches the wire, judged by admission
        tenant = kwargs.pop("tenant", None)
        sequence_id = kwargs.get("sequence_id", 0)
        if self._admission is None:
            try:
                return self._infer_routed(model_name, inputs, kwargs,
                                          sequence_id, affinity_key)
            except AdmissionRejected as e:
                self._admission_note_shed(e)  # endpoint-limiter shed
                raise
        token = self._admission_begin(kwargs, sequence_id, tenant)
        t0 = time.monotonic()
        try:
            result = self._infer_routed(model_name, inputs, kwargs,
                                        sequence_id, affinity_key)
        except BaseException as e:
            self._admission_settle(token, t0, e)
            raise
        self._admission_settle(token, t0, None)
        return result

    def _infer_routed(self, model_name: str, inputs, kwargs,
                      sequence_id: int, affinity_key: Optional[str] = None):
        timeout_s = kwargs.get("client_timeout")
        request_id = kwargs.get("request_id", "")
        if sequence_id:
            return self._sequence_infer(model_name, inputs, kwargs,
                                        affinity_key)
        if self._hedge is not None:
            # hedged attempts run on executor threads that don't inherit
            # this context: a stashed admission phase would never be
            # claimed and could leak onto a later unrelated span
            consume_admission_phase()
            return self._hedged_infer(model_name, inputs, kwargs, timeout_s,
                                      affinity_key)

        def op(client, remaining):
            kw = dict(kwargs)
            if remaining is not None:
                kw["client_timeout"] = remaining
            return client.infer(model_name, inputs, **kw)

        return self._execute(
            op, idempotent=True, timeout_s=timeout_s,
            request_id=request_id, sequence_id=sequence_id,
            record_latency=True, affinity_key=affinity_key)

    def _sequence_infer(self, model_name: str, inputs, kwargs,
                        affinity_key: Optional[str] = None):
        """Affinity-pinned sequence request: every request of one sequence
        lands on the pinned replica. Connect failures re-attempt (the pin
        moves only while the sequence has no established server state);
        in-flight deaths abandon the sequence — never silently re-sent."""
        sequence_id = kwargs["sequence_id"]
        request_id = kwargs.get("request_id", "")
        budget = AttemptBudget(self._budget_policy, kwargs.get("client_timeout"))
        tried: List[EndpointState] = []
        last: Optional[BaseException] = None
        for _ in range(self._max_failover_attempts):
            try:
                remaining = budget.attempt_timeout_s()
            except InferenceServerException as deadline_exc:
                if last is not None:
                    raise deadline_exc from last
                raise
            ep = self._seq_endpoint(sequence_id, exclude=tried,
                                    affinity_key=affinity_key)
            if ep not in tried:
                tried.append(ep)
            _flight.note("pool", "route", url=ep.url,
                         sequence_id=sequence_id)
            self.pool.begin(ep)
            t0 = time.monotonic()
            try:
                kw = dict(kwargs)
                if remaining is not None:
                    kw["client_timeout"] = remaining
                result = ep.client.infer(model_name, inputs, **kw)
            except CircuitOpenError as e:
                last = e  # nothing was sent; the pinned replica is retried
                time.sleep(self._seq_backoff_s(len(tried), budget))
                continue
            except Exception as e:
                domain = self._record_attempt_failure(ep, e)
                if domain in (FATAL, SHED):
                    raise  # neither outcome is servable elsewhere
                last = e
                if domain == CONNECT:
                    if self._seq_repin_allowed(sequence_id):
                        # no request of this sequence ever landed: there is
                        # no replica-local state, the pin may move
                        self._seq_unpin(sequence_id)
                    else:
                        # one legal endpoint: back off instead of burning
                        # every attempt inside a sub-second connect blip
                        time.sleep(self._seq_backoff_s(len(tried), budget))
                    continue
                # transient/timeout: the request may have reached the
                # replica — the sequence state is unknowable, abandon it
                self._sequence_event(ep, request_id, sequence_id, e)
                self._seq_unpin(sequence_id)
                raise
            finally:
                self.pool.done(ep)
            self.pool.record_success(ep, time.monotonic() - t0)
            self._seq_mark_established(sequence_id)
            if kwargs.get("sequence_end"):
                self._seq_unpin(sequence_id)
            return result
        assert last is not None
        raise last

    def pinned_infer(self, url: str, model_name: str, inputs, *args,
                     **kwargs):
        """ONE infer against the named replica: no routing, no failover,
        no hedging, and no pool-level admission gate — the sharded
        scatter-gather layer (``client_tpu.shard``) owns retry/admission
        semantics per LOGICAL request and pins each shard here. The
        outcome still feeds the endpoint's breaker, outlier detector,
        outstanding count and latency window exactly like a routed
        attempt, so shard traffic is visible to ``least_outstanding``
        routing and health accounting (shard-aware routing)."""
        kwargs = _fold_infer_args(args, kwargs)
        ep = self.pool.endpoint_by_url(url)
        self.pool.begin(ep)
        t0 = time.monotonic()
        try:
            result = ep.client.infer(model_name, inputs, **kwargs)
        except CircuitOpenError:
            raise  # nothing was sent; the breaker already knows
        except Exception as e:
            self._record_attempt_failure(ep, e)
            raise
        finally:
            self.pool.done(ep)
        self.pool.record_success(ep, time.monotonic() - t0)
        return result

    def routed_infer(self, model_name: str, inputs, *args, **kwargs):
        """One pool-routed infer WITHOUT the pool-level admission gate:
        full routing/failover/hedging, but admission belongs to the
        caller — the pipeline layer (``client_tpu.pipeline``) charges
        ONE token per logical DAG run and dispatches each unpinned
        stage here (the ``pinned_infer`` contract, minus the pin).
        ``affinity_key=`` still lands the request on its key's home
        replica under ``routing="affinity"``."""
        kwargs = _fold_infer_args(args, kwargs)
        affinity_key = kwargs.pop("affinity_key", None)
        kwargs.pop("tenant", None)
        sequence_id = kwargs.get("sequence_id", 0)
        try:
            return self._infer_routed(model_name, inputs, kwargs,
                                      sequence_id, affinity_key)
        except AdmissionRejected as e:
            self._admission_note_shed(e)  # endpoint-limiter shed
            raise

    def pinned_generate_stream(self, url: str, *args, **kwargs):
        """One SSE generate stream against the named replica: no routing,
        no failover and no pool-level admission gate — the disaggregated
        prefill/decode layer (``client_tpu.disagg``) pins its decode leg
        here and owns retry/admission per LOGICAL session. The endpoint's
        ``outstanding`` slot is held for the life of the iteration and
        the outcome feeds its breaker/outlier/latency accounting exactly
        like a routed stream."""
        ep = self.pool.endpoint_by_url(url)
        inner = ep.client.generate_stream(*args, **kwargs)  # lazy: no I/O yet

        def stream():
            self.pool.begin(ep)
            ok = True
            try:
                for item in inner:
                    yield item
            except Exception as e:
                ok = False
                self._record_attempt_failure(ep, e)
                raise
            finally:
                self.pool.done(ep)
                if ok:
                    self.pool.record_success(ep)

        return stream()

    def _get_executor(self) -> ThreadPoolExecutor:
        with self._executor_lock:
            if self._executor is None:
                self._executor = ThreadPoolExecutor(
                    max_workers=self._hedge_executor_workers,
                    thread_name_prefix="client_tpu_pool_hedge")
            return self._executor

    def _hedged_infer(self, model_name, inputs, kwargs,
                      timeout_s: Optional[float],
                      affinity_key: Optional[str] = None):
        """Primary + up to ``max_hedges`` staggered copies on distinct
        replicas; first success wins, losers are cancelled best-effort
        (a thread-borne attempt that already started runs to completion
        in the background and still records its outcome). With an
        affinity key the primary goes home; hedges exclude it, so a hedge
        is the key's deterministic rendezvous runner-up."""
        budget = AttemptBudget(self._budget_policy, timeout_s)
        hedge = self._hedge
        pool = self.pool
        executor = self._get_executor()
        tried: List[EndpointState] = []
        failures: List[BaseException] = []
        futures: List[Any] = []

        def attempt(ep, remaining):
            pool.begin(ep)
            t0 = time.monotonic()
            try:
                kw = dict(kwargs)
                if remaining is not None:
                    kw["client_timeout"] = remaining
                result = ep.client.infer(model_name, inputs, **kw)
            except Exception as e:
                self._record_attempt_failure(ep, e)
                raise
            finally:
                pool.done(ep)
            pool.record_success(ep, time.monotonic() - t0)
            return result

        def spawn():
            remaining = budget.attempt_timeout_s()  # raises once spent
            ep = pool.select(exclude=tried, affinity_key=affinity_key)
            tried.append(ep)
            _flight.note("pool", "route", url=ep.url, attempt=len(tried))
            future = executor.submit(attempt, ep, remaining)
            futures.append(future)
            return future

        tel = self._telemetry
        hedge_futures: set = set()  # attempts fired BY the hedge timer
        max_attempts = max(self._max_failover_attempts, 1 + hedge.max_hedges)
        spawn()
        hedges_left = hedge.max_hedges
        hedge_at = time.monotonic() + hedge.delay(
            pool.latency_p95(hedge.min_latency_samples), self._rng)
        while True:
            timeout = None
            if hedges_left > 0:
                timeout = max(0.0, hedge_at - time.monotonic())
            done, _ = wait(futures, timeout=timeout,
                           return_when=FIRST_COMPLETED)
            for f in done:
                futures.remove(f)
                try:
                    result = f.result()
                except Exception as e:
                    if (not isinstance(e, CircuitOpenError)
                            and classify_fault(e) in (FATAL, SHED)):
                        for p in futures:
                            p.cancel()
                        raise  # the server answered; racing more copies won't help
                    failures.append(e)
                else:
                    for p in futures:
                        p.cancel()
                    if hedge_futures:
                        # a hedge raced this request: did it beat the primary?
                        _flight.note(
                            "hedge",
                            "win" if f in hedge_futures else "loss")
                        if tel is not None:
                            tel.on_hedge_result(f in hedge_futures)
                    return result
            firing = hedges_left > 0 and time.monotonic() >= hedge_at
            if futures and not firing:
                continue
            # need a fresh attempt: the hedge timer fired, or every
            # in-flight attempt has failed (failover inside the hedge path)
            if len(tried) >= max_attempts:
                if futures:
                    hedges_left = 0
                    continue
                raise failures[-1]
            try:
                spawned = spawn()
            except (NoEndpointAvailableError, InferenceServerException) as e:
                if futures:
                    hedges_left = 0  # nothing to hedge to; ride out in-flight
                    continue
                if failures:
                    raise failures[-1] from e
                raise
            if firing:
                hedge_futures.add(spawned)
                _flight.note("hedge", "launch", url=tried[-1].url)
                if tel is not None:
                    tel.on_hedge_fired()
                hedges_left -= 1
                hedge_at = time.monotonic() + hedge.delay(
                    pool.latency_p95(hedge.min_latency_samples), self._rng)

    # -- streaming (HTTP generate extension) ----------------------------------
    def generate_stream(self, *args, **kwargs):
        """Pool-routed SSE generate stream. The endpoint's ``outstanding``
        count stays held until the stream is exhausted (or abandoned), so
        ``least_outstanding`` routing sees long-lived generations — a bare
        delegation would release the slot as soon as the iterator is
        returned, before a single event streamed. With admission armed the
        stream holds one slot for its whole life (admitted on first
        iteration, like the outstanding count; released without feeding
        the limiter — an SSE session's duration is not a unary RTT).
        ``affinity_key=`` (with ``routing="affinity"``) lands the session
        on its key's home replica, so a re-opened generation finds its
        KV cache."""
        affinity_key = kwargs.pop("affinity_key", None)
        tenant = kwargs.pop("tenant", None)
        try:
            ep = self.pool.select(affinity_key=affinity_key)
        except AdmissionRejected as e:
            self._admission_note_shed(e)
            raise
        inner = ep.client.generate_stream(*args, **kwargs)  # lazy: no I/O yet

        def stream():
            # begin/done pair with actual iteration (the underlying client
            # generator only issues the request on first next); a returned-
            # but-never-iterated stream holds no slot (nor admission)
            token = None
            if self._admission is not None:
                try:
                    token = self._admission.acquire(tenant=tenant)
                except AdmissionRejected as e:
                    self._admission_note_shed(e)
                    raise
            self.pool.begin(ep)
            ok = True
            tel = self._telemetry
            t0 = time.monotonic() if tel is not None else 0.0
            first = tel is not None
            try:
                for item in inner:
                    if first:
                        # per-endpoint TTFT feed: one windowed observation
                        # per stream, so ejection decisions have a latency
                        # signal per replica (scrape shows
                        # client_tpu_pool_endpoint_ttft_ms)
                        first = False
                        tel.observe_endpoint_ttft(
                            ep.url, (time.monotonic() - t0) * 1e3)
                    yield item
            except Exception as e:
                ok = False
                self._record_attempt_failure(ep, e)
                raise
            finally:
                # abandonment closes the generator -> GeneratorExit runs
                # this too, releasing the outstanding slot
                self.pool.done(ep)
                if token is not None:
                    token.release()
                if ok:
                    self.pool.record_success(ep)

        return stream()

    # -- streaming (GRPC): pinned to ONE endpoint -----------------------------
    def start_stream(self, *args, **kwargs):
        """Open a bidi stream on ONE selected endpoint and pin it there:
        stream state lives on a single client, so ``async_stream_infer`` /
        ``stop_stream`` route to the same endpoint until the stream stops
        (combine with ``auto_reconnect=True`` for same-endpoint recovery).
        Streams are never failed over — sequence state is server-local."""
        with self._stream_lock:
            if self._stream_ep is not None:
                raise InferenceServerException(
                    "cannot start a stream: one is already active; stop it first")
            ep = self.pool.select()
            result = ep.client.start_stream(*args, **kwargs)
            self._stream_ep = ep
            return result

    def async_stream_infer(self, *args, **kwargs):
        with self._stream_lock:
            ep = self._stream_ep
        if ep is None:
            raise InferenceServerException(
                "stream not available: call start_stream first")
        return ep.client.async_stream_infer(*args, **kwargs)

    def stop_stream(self, *args, **kwargs):
        with self._stream_lock:
            ep = self._stream_ep
        if ep is None:
            return None
        try:
            return ep.client.stop_stream(*args, **kwargs)
        finally:
            # release the pin even when stop raised: the grpc client clears
            # its own stream state before closing, so a retried start_stream
            # must not stay wedged behind a stale pin
            with self._stream_lock:
                if self._stream_ep is ep:
                    self._stream_ep = None

    # -- generic surface delegation -------------------------------------------
    def _broadcast(self, name: str, args, kwargs):
        """Apply a state-mutating method to EVERY endpoint; every endpoint
        is attempted even if one fails, then the first failure raises."""
        first_exc: Optional[BaseException] = None
        result = None
        for ep in self.pool.endpoints:
            try:
                result = getattr(ep.client, name)(*args, **kwargs)
            except Exception as e:
                if first_exc is None:
                    first_exc = e
        if first_exc is not None:
            raise first_exc
        return result

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        probe = getattr(self.pool.endpoints[0].client, name, None)
        if not callable(probe):
            raise AttributeError(
                f"{type(self).__name__} has no attribute {name!r}")

        if self._is_broadcast(name):
            def call(*args, **kwargs):
                return self._broadcast(name, args, kwargs)
        else:
            def call(*args, **kwargs):
                def op(client, _remaining):
                    return getattr(client, name)(*args, **kwargs)
                return self._execute(op, idempotent=True)

        call.__name__ = name
        return call


class AioPoolClient(_PoolClientBase):
    """Asyncio twin of :class:`PoolClient` over the aio HTTP/GRPC frontends.

    The health prober runs as an asyncio task, started lazily on the first
    pooled call (or explicitly via :meth:`start`); hedged attempts are
    asyncio tasks, so the losing hedge is truly cancelled mid-flight."""

    _AIO = True

    def __init__(self, urls, **kwargs):
        super().__init__(urls, **kwargs)
        self._probe_task = None

    # -- lifecycle -----------------------------------------------------------
    async def start(self) -> "AioPoolClient":
        self._ensure_prober()
        return self

    def _ensure_prober(self) -> None:
        if (self._probe_task is None and self._health_interval_s
                and not self._closed):
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                return  # no loop yet; the next in-loop call starts it
            self._probe_task = loop.create_task(self._probe_loop())

    async def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._probe_task is not None:
            self._probe_task.cancel()
            try:
                await self._probe_task
            except BaseException:
                pass
            self._probe_task = None
        for ep in self.pool.endpoints:
            try:
                await ep.client.close()
            except Exception:
                pass

    async def __aenter__(self) -> "AioPoolClient":
        self._ensure_prober()
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    # -- active health probing ----------------------------------------------
    async def _probe_one(self, ep: EndpointState) -> None:
        try:
            ok = await ep.client.is_server_ready(
                probe=True, client_timeout=self._probe_timeout_s)
        except Exception:
            ok = False
        self.pool.set_health(ep, ok)

    async def _probe_once(self) -> None:
        # concurrent (see the sync twin): one hung endpoint must not
        # delay every other endpoint's probe by probe_timeout_s
        await asyncio.gather(
            *(self._probe_one(ep) for ep in self.pool.endpoints))

    async def _probe_loop(self) -> None:
        while True:
            await asyncio.sleep(self._health_interval_s)
            await self._probe_once()
            # idle-pin sweep on the prober cadence (see the sync twin);
            # _seq_gc never blocks beyond one short lock
            self._seq_gc()

    # -- failover engine ------------------------------------------------------
    async def _execute(self, op, idempotent: bool = True,
                       timeout_s: Optional[float] = None,
                       request_id: str = "", sequence_id: int = 0,
                       record_latency: bool = False,
                       affinity_key: Optional[str] = None):
        self._ensure_prober()
        budget = AttemptBudget(self._budget_policy, timeout_s)
        tried: List[EndpointState] = []
        last: Optional[BaseException] = None
        while len(tried) < self._max_failover_attempts:
            try:
                remaining = budget.attempt_timeout_s()
            except InferenceServerException as deadline_exc:
                if last is not None:
                    raise deadline_exc from last
                raise
            try:
                ep = self.pool.select(exclude=tried,
                                      affinity_key=affinity_key)
            except NoEndpointAvailableError:
                if last is not None:
                    raise last
                raise
            tried.append(ep)
            _flight.note("pool", "route", url=ep.url, attempt=len(tried))
            self.pool.begin(ep)
            t0 = time.monotonic()
            try:
                result = await op(ep.client, remaining)
            except CircuitOpenError as e:
                last = e
                _flight.note("pool", "failover", url=ep.url,
                             domain="circuit_open")
                continue
            except Exception as e:
                domain = self._record_attempt_failure(ep, e)
                if domain == INVALID:
                    # answered WRONG: never same-endpoint retried; fail
                    # over iff idempotent (see the sync twin)
                    last = e
                    if not idempotent:
                        self._sequence_event(ep, request_id, sequence_id, e)
                        raise
                    _flight.note("pool", "failover", url=ep.url,
                                 domain=domain)
                    continue
                if domain in (FATAL, SHED):
                    raise  # neither outcome is servable elsewhere
                last = e
                if domain in (TRANSIENT, TIMEOUT) and not idempotent:
                    self._sequence_event(ep, request_id, sequence_id, e)
                    raise
                _flight.note("pool", "failover", url=ep.url, domain=domain)
                continue
            finally:
                self.pool.done(ep)
            self.pool.record_success(
                ep, time.monotonic() - t0 if record_latency else None)
            return result
        assert last is not None
        raise last

    # -- admission gate -------------------------------------------------------
    async def _admission_begin(self, kwargs, sequence_id: int,
                               tenant: Optional[str] = None):
        """Async twin of the sync gate (see ``PoolClient._admission_begin``)."""
        ctrl = self._admission
        force = bool(sequence_id) and not self._seq_repin_allowed(sequence_id)
        deadline = self._admission_deadline(kwargs.get("client_timeout"))
        t0_ns = time.perf_counter_ns()
        token = await ctrl.acquire_async(
            kwargs.get("priority") or 0, deadline, force=force,
            tenant=tenant)
        if token.waited_s and self._telemetry is not None:
            # see the sync twin: stash only when a span can claim it
            stash_admission_phase(t0_ns, time.perf_counter_ns())
        return token

    # -- inference -------------------------------------------------------------
    async def infer(self, model_name: str, inputs, *args, **kwargs):
        """Pool-routed async ``infer`` (same affinity/idempotency/hedging
        and admission contract as the sync twin)."""
        kwargs = _fold_infer_args(args, kwargs)
        scratch = _flight.layer_begin(self._telemetry, "pool", model_name)
        if scratch is None:
            return await self._infer_gated(model_name, inputs, kwargs)
        try:
            result = await self._infer_gated(model_name, inputs, kwargs)
        except BaseException as e:
            _flight.layer_commit(self._telemetry, scratch, error=e)
            raise
        _flight.layer_commit(self._telemetry, scratch)
        return result

    async def _infer_gated(self, model_name: str, inputs, kwargs):
        """Async twin of the sync ``_infer_gated`` split."""
        affinity_key = kwargs.pop("affinity_key", None)
        tenant = kwargs.pop("tenant", None)
        sequence_id = kwargs.get("sequence_id", 0)
        if self._admission is None:
            try:
                return await self._infer_routed(model_name, inputs, kwargs,
                                                sequence_id, affinity_key)
            except AdmissionRejected as e:
                self._admission_note_shed(e)  # endpoint-limiter shed
                raise
        token = await self._admission_begin(kwargs, sequence_id, tenant)
        t0 = time.monotonic()
        try:
            result = await self._infer_routed(model_name, inputs, kwargs,
                                              sequence_id, affinity_key)
        except BaseException as e:
            self._admission_settle(token, t0, e)
            raise
        self._admission_settle(token, t0, None)
        return result

    async def _infer_routed(self, model_name: str, inputs, kwargs,
                            sequence_id: int,
                            affinity_key: Optional[str] = None):
        timeout_s = kwargs.get("client_timeout")
        request_id = kwargs.get("request_id", "")
        if sequence_id:
            return await self._sequence_infer(model_name, inputs, kwargs,
                                              affinity_key)
        if self._hedge is not None:
            # hedge tasks share this task's context, but racing attempts
            # would each claim-or-miss the one stashed phase
            # nondeterministically — drop it instead (see the sync twin)
            consume_admission_phase()
            return await self._hedged_infer(
                model_name, inputs, kwargs, timeout_s, affinity_key)

        async def op(client, remaining):
            kw = dict(kwargs)
            if remaining is not None:
                kw["client_timeout"] = remaining
            return await client.infer(model_name, inputs, **kw)

        return await self._execute(
            op, idempotent=True, timeout_s=timeout_s,
            request_id=request_id, sequence_id=sequence_id,
            record_latency=True, affinity_key=affinity_key)

    async def _sequence_infer(self, model_name: str, inputs, kwargs,
                              affinity_key: Optional[str] = None):
        """Async twin of the sync affinity-pinned sequence path."""
        self._ensure_prober()
        sequence_id = kwargs["sequence_id"]
        request_id = kwargs.get("request_id", "")
        budget = AttemptBudget(self._budget_policy, kwargs.get("client_timeout"))
        tried: List[EndpointState] = []
        last: Optional[BaseException] = None
        for _ in range(self._max_failover_attempts):
            try:
                remaining = budget.attempt_timeout_s()
            except InferenceServerException as deadline_exc:
                if last is not None:
                    raise deadline_exc from last
                raise
            ep = self._seq_endpoint(sequence_id, exclude=tried,
                                    affinity_key=affinity_key)
            if ep not in tried:
                tried.append(ep)
            _flight.note("pool", "route", url=ep.url,
                         sequence_id=sequence_id)
            self.pool.begin(ep)
            t0 = time.monotonic()
            try:
                kw = dict(kwargs)
                if remaining is not None:
                    kw["client_timeout"] = remaining
                result = await ep.client.infer(model_name, inputs, **kw)
            except CircuitOpenError as e:
                last = e
                await asyncio.sleep(self._seq_backoff_s(len(tried), budget))
                continue
            except Exception as e:
                domain = self._record_attempt_failure(ep, e)
                if domain in (FATAL, SHED):
                    raise  # neither outcome is servable elsewhere
                last = e
                if domain == CONNECT:
                    if self._seq_repin_allowed(sequence_id):
                        self._seq_unpin(sequence_id)
                    else:
                        await asyncio.sleep(
                            self._seq_backoff_s(len(tried), budget))
                    continue
                self._sequence_event(ep, request_id, sequence_id, e)
                self._seq_unpin(sequence_id)
                raise
            finally:
                self.pool.done(ep)
            self.pool.record_success(ep, time.monotonic() - t0)
            self._seq_mark_established(sequence_id)
            if kwargs.get("sequence_end"):
                self._seq_unpin(sequence_id)
            return result
        assert last is not None
        raise last

    async def pinned_infer(self, url: str, model_name: str, inputs, *args,
                           **kwargs):
        """Async twin of the sync :meth:`PoolClient.pinned_infer` (the
        sharded scatter-gather layer's per-shard dispatch)."""
        self._ensure_prober()
        kwargs = _fold_infer_args(args, kwargs)
        ep = self.pool.endpoint_by_url(url)
        self.pool.begin(ep)
        t0 = time.monotonic()
        try:
            result = await ep.client.infer(model_name, inputs, **kwargs)
        except asyncio.CancelledError:
            raise  # a cancelled sibling shard: no outcome to record
        except CircuitOpenError:
            raise
        except Exception as e:
            self._record_attempt_failure(ep, e)
            raise
        finally:
            self.pool.done(ep)
        self.pool.record_success(ep, time.monotonic() - t0)
        return result

    async def routed_infer(self, model_name: str, inputs, *args,
                           **kwargs):
        """Async twin of the sync :meth:`PoolClient.routed_infer` (the
        pipeline layer's per-stage dispatch: routed, admission-free)."""
        self._ensure_prober()
        kwargs = _fold_infer_args(args, kwargs)
        affinity_key = kwargs.pop("affinity_key", None)
        kwargs.pop("tenant", None)
        sequence_id = kwargs.get("sequence_id", 0)
        try:
            return await self._infer_routed(model_name, inputs, kwargs,
                                            sequence_id, affinity_key)
        except AdmissionRejected as e:
            self._admission_note_shed(e)
            raise

    # -- streaming (HTTP generate extension) ----------------------------------
    def generate_stream(self, *args, **kwargs):
        """Pool-routed async SSE generate stream; the endpoint's
        ``outstanding`` slot — and, with admission armed, one admission
        slot — is held for the life of the iteration (see the sync
        twin). ``affinity_key=`` lands the session on its key's home
        replica under ``routing="affinity"``."""
        self._ensure_prober()  # streaming-only pools still need health
        affinity_key = kwargs.pop("affinity_key", None)
        tenant = kwargs.pop("tenant", None)
        try:
            ep = self.pool.select(affinity_key=affinity_key)
        except AdmissionRejected as e:
            self._admission_note_shed(e)
            raise
        inner = ep.client.generate_stream(*args, **kwargs)  # lazy: no I/O yet

        async def stream():
            self._ensure_prober()  # called outside a loop? start it here
            token = None
            if self._admission is not None:
                try:
                    token = await self._admission.acquire_async(tenant=tenant)
                except AdmissionRejected as e:
                    self._admission_note_shed(e)
                    raise
            self.pool.begin(ep)
            ok = True
            tel = self._telemetry
            t0 = time.monotonic() if tel is not None else 0.0
            first = tel is not None
            try:
                async for item in inner:
                    if first:
                        # per-endpoint TTFT feed (see the sync twin)
                        first = False
                        tel.observe_endpoint_ttft(
                            ep.url, (time.monotonic() - t0) * 1e3)
                    yield item
            except Exception as e:
                ok = False
                self._record_attempt_failure(ep, e)
                raise
            finally:
                self.pool.done(ep)
                if token is not None:
                    token.release()
                if ok:
                    self.pool.record_success(ep)

        return stream()

    def pinned_generate_stream(self, url: str, *args, **kwargs):
        """Async twin of the sync :meth:`PoolClient.pinned_generate_stream`
        (the disaggregated decode leg's replica-pinned SSE stream)."""
        self._ensure_prober()
        ep = self.pool.endpoint_by_url(url)
        inner = ep.client.generate_stream(*args, **kwargs)  # lazy: no I/O yet

        async def stream():
            self.pool.begin(ep)
            ok = True
            try:
                async for item in inner:
                    yield item
            except Exception as e:
                ok = False
                self._record_attempt_failure(ep, e)
                raise
            finally:
                self.pool.done(ep)
                if ok:
                    self.pool.record_success(ep)

        return stream()

    async def _hedged_infer(self, model_name, inputs, kwargs,
                            timeout_s: Optional[float],
                            affinity_key: Optional[str] = None):
        self._ensure_prober()
        budget = AttemptBudget(self._budget_policy, timeout_s)
        hedge = self._hedge
        pool = self.pool
        tried: List[EndpointState] = []
        failures: List[BaseException] = []
        tasks: "set" = set()

        async def attempt(ep, remaining):
            pool.begin(ep)
            t0 = time.monotonic()
            try:
                kw = dict(kwargs)
                if remaining is not None:
                    kw["client_timeout"] = remaining
                result = await ep.client.infer(model_name, inputs, **kw)
            except asyncio.CancelledError:
                raise  # the losing hedge: no outcome to record
            except Exception as e:
                self._record_attempt_failure(ep, e)
                raise
            finally:
                pool.done(ep)
            pool.record_success(ep, time.monotonic() - t0)
            return result

        def spawn():
            remaining = budget.attempt_timeout_s()
            ep = pool.select(exclude=tried, affinity_key=affinity_key)
            tried.append(ep)
            _flight.note("pool", "route", url=ep.url, attempt=len(tried))
            task = asyncio.ensure_future(attempt(ep, remaining))
            tasks.add(task)
            return task

        async def cancel_pending():
            for t in tasks:
                t.cancel()
            for t in tasks:
                try:
                    await t
                except BaseException:
                    pass

        tel = self._telemetry
        hedge_tasks: set = set()  # attempts fired BY the hedge timer
        max_attempts = max(self._max_failover_attempts, 1 + hedge.max_hedges)
        spawn()
        hedges_left = hedge.max_hedges
        hedge_at = time.monotonic() + hedge.delay(
            pool.latency_p95(hedge.min_latency_samples), self._rng)
        try:
            while True:
                timeout = None
                if hedges_left > 0:
                    timeout = max(0.0, hedge_at - time.monotonic())
                done, _ = await asyncio.wait(
                    tasks, timeout=timeout,
                    return_when=asyncio.FIRST_COMPLETED)
                for t in done:
                    tasks.discard(t)
                    try:
                        result = t.result()
                    except Exception as e:
                        if (not isinstance(e, CircuitOpenError)
                                and classify_fault(e) in (FATAL, SHED)):
                            await cancel_pending()
                            raise
                        failures.append(e)
                    else:
                        await cancel_pending()
                        if hedge_tasks:
                            _flight.note(
                                "hedge",
                                "win" if t in hedge_tasks else "loss")
                            if tel is not None:
                                tel.on_hedge_result(t in hedge_tasks)
                        return result
                firing = hedges_left > 0 and time.monotonic() >= hedge_at
                if tasks and not firing:
                    continue
                if len(tried) >= max_attempts:
                    if tasks:
                        hedges_left = 0
                        continue
                    raise failures[-1]
                try:
                    spawned = spawn()
                except (NoEndpointAvailableError, InferenceServerException) as e:
                    if tasks:
                        hedges_left = 0
                        continue
                    if failures:
                        raise failures[-1] from e
                    raise
                if firing:
                    hedge_tasks.add(spawned)
                    _flight.note("hedge", "launch", url=tried[-1].url)
                    if tel is not None:
                        tel.on_hedge_fired()
                    hedges_left -= 1
                    hedge_at = time.monotonic() + hedge.delay(
                        pool.latency_p95(hedge.min_latency_samples), self._rng)
        except asyncio.CancelledError:
            # external cancellation (wait_for timeout, caller teardown):
            # the in-flight attempts must die with the caller, not keep
            # loading replicas in the background
            await cancel_pending()
            raise

    # -- generic surface delegation -------------------------------------------
    async def _broadcast(self, name: str, args, kwargs):
        """Async twin of the sync broadcast: every endpoint is attempted
        even if one fails, then the first failure raises. Handles the sync
        methods the aio clients inherit (register_plugin etc.)."""
        first_exc: Optional[BaseException] = None
        result = None
        for ep in self.pool.endpoints:
            try:
                result = getattr(ep.client, name)(*args, **kwargs)
                if inspect.isawaitable(result):
                    result = await result
            except Exception as e:
                if first_exc is None:
                    first_exc = e
        if first_exc is not None:
            raise first_exc
        return result

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        probe = getattr(self.pool.endpoints[0].client, name, None)
        if not callable(probe):
            raise AttributeError(
                f"{type(self).__name__} has no attribute {name!r}")

        if self._is_broadcast(name):
            async def call(*args, **kwargs):
                return await self._broadcast(name, args, kwargs)
        else:
            async def call(*args, **kwargs):
                async def op(client, _remaining):
                    # the aio clients inherit a few sync methods from the
                    # shared base (plugins); awaiting their None would throw
                    result = getattr(client, name)(*args, **kwargs)
                    if inspect.isawaitable(result):
                        result = await result
                    return result
                return await self._execute(op, idempotent=True)

        call.__name__ = name
        return call
