"""System (POSIX) shared-memory regions for the zero-copy data plane.

Function-for-function parity with the reference's
``tritonclient.utils.shared_memory`` (utils/shared_memory/__init__.py:39-251):
create/set/get/destroy plus the process-global key bookkeeping that makes
multiple handles over one key safe. Backed by
``multiprocessing.shared_memory`` (no C extension needed).

Flow (SURVEY.md §3.5): create a region here, ``register_system_shared_memory``
it with the server, point ``InferInput.set_shared_memory`` /
``InferRequestedOutput.set_shared_memory`` at it, and tensor bytes never ride
the wire.
"""

from __future__ import annotations

import threading
from multiprocessing import shared_memory as mpshm
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import (
    InferenceServerException,
    serialize_bf16_tensor,
    serialize_byte_tensor,
    serialized_byte_size,
    triton_to_np_dtype,
)
# data-plane accounting: every lifecycle/map op consults the process-global
# recorder (observe._DATAPLANE); with none installed the cost is one module
# attribute load + None check per op (the pay-for-what-you-use bar)
from ... import observe as _observe


class SharedMemoryException(InferenceServerException):
    """Raised on shared-memory lifecycle/bounds errors."""


def _posix_name(key: str) -> str:
    # POSIX shm keys are conventionally written "/name"; the stdlib module
    # wants the bare name.
    return key.lstrip("/")


def _untrack(shm: mpshm.SharedMemory) -> None:
    # Python 3.12's resource_tracker registers every mapping (even attaches)
    # and unlinks at process exit; ownership here is explicit, so deregister.
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:
        pass


# POSIX names created (and therefore legitimately resource-tracked) by this
# process; attaches to these must NOT untrack, or the tracker loses the
# creator's entry (tracker state is a set keyed by name).
_owned_names: set = set()


def attach_shared_memory(key: str) -> mpshm.SharedMemory:
    """Attach to an existing POSIX region without taking unlink ownership."""
    shm = mpshm.SharedMemory(name=_posix_name(key))
    if _posix_name(key) not in _owned_names:
        _untrack(shm)
    return shm


# Mappings whose close() failed because zero-copy numpy views still alias
# them; kept referenced so the views stay valid, retried on later closes
# (most views die quickly — e.g. a server response that served a zero-copy
# read), unmapped at process exit at the latest.
_deferred_unmaps: List[mpshm.SharedMemory] = []
_deferred_lock = threading.Lock()


def _sweep_deferred() -> None:
    """Retry deferred unmaps whose aliasing views have since died.

    Without this, a register/read/unregister churn leaks one mapping + fd
    per cycle (the 2026-07 soak hit EMFILE server-side after ~500 cycles):
    each close() raised BufferError while the response still aliased the
    buffer, and the mapping was parked forever. The views are dead by the
    next cycle — so each sweep closes the previous casualties and the
    steady state is O(live views), not O(cycles)."""
    with _deferred_lock:
        parked, _deferred_unmaps[:] = list(_deferred_unmaps), []
    survivors = []
    try:
        for old in parked:
            try:
                # the instance's close was neutralized when parked; go
                # through the class so the retry actually runs
                mpshm.SharedMemory.close(old)
            except BufferError:
                survivors.append(old)
            except Exception:
                # half-closed mapping (e.g. os.close failing): parking it
                # again keeps the retry path alive instead of dropping the
                # fd on the floor — and the sweep stays best-effort
                survivors.append(old)
    finally:
        with _deferred_lock:
            _deferred_unmaps.extend(survivors)


def _safe_close(shm: mpshm.SharedMemory, unlink: bool) -> None:
    _sweep_deferred()
    if unlink:
        try:
            shm.unlink()
        except FileNotFoundError:
            pass
    try:
        shm.close()
    except BufferError:
        # np.frombuffer views over the mapping are still alive; the POSIX
        # object is already unlinked (if owned) — park the mapping so the
        # views stay valid, neutralize __del__'s retry so it can't raise,
        # and let a later sweep (or process exit) finish the unmap.
        shm.close = lambda: None
        with _deferred_lock:
            _deferred_unmaps.append(shm)


class SharedMemoryRegion:
    """Handle to a created-or-attached system shared-memory region."""

    def __init__(self, triton_shm_name: str, shm_key: str):
        self._triton_shm_name = triton_shm_name
        self._shm_key = shm_key
        self._shm: Optional[mpshm.SharedMemory] = None
        self._byte_size = 0

    # accessors used by examples/tests and the perf harness
    @property
    def name(self) -> str:
        return self._triton_shm_name

    @property
    def key(self) -> str:
        return self._shm_key

    @property
    def byte_size(self) -> int:
        return self._byte_size

    def buf(self) -> memoryview:
        if self._shm is None:
            raise SharedMemoryException("shared-memory region is not mapped")
        return self._shm.buf

    def __repr__(self) -> str:
        return (
            f"SharedMemoryRegion(name={self._triton_shm_name!r}, "
            f"key={self._shm_key!r}, byte_size={self._byte_size})"
        )


# Process-global bookkeeping: one underlying mapping may back several handles
# (attach-or-create); unlink only when the last handle is destroyed.
_lock = threading.Lock()
_key_refcount: Dict[str, int] = {}
_active_regions: List[SharedMemoryRegion] = []


def create_shared_memory_region(
    triton_shm_name: str, key: str, byte_size: int, create_only: bool = False
) -> SharedMemoryRegion:
    """Create (or attach to) the POSIX region ``key`` of ``byte_size`` bytes."""
    if byte_size <= 0:
        raise SharedMemoryException("shared-memory byte_size must be positive")
    handle = SharedMemoryRegion(triton_shm_name, key)
    name = _posix_name(key)
    created = True
    with _lock:
        try:
            # created regions stay resource-tracked: unlink() deregisters, and
            # the tracker cleans up if the process dies before destroy
            handle._shm = mpshm.SharedMemory(name=name, create=True, size=byte_size)
            _owned_names.add(name)
        except FileExistsError:
            if create_only:
                raise SharedMemoryException(
                    f"unable to create the shared memory region with key '{key}': "
                    "already exists"
                )
            try:
                handle._shm = attach_shared_memory(key)
            except FileNotFoundError:
                raise SharedMemoryException(
                    f"unable to attach to shared memory region with key '{key}'"
                )
            if handle._shm.size < byte_size:
                handle._shm.close()
                raise SharedMemoryException(
                    f"existing shared memory region with key '{key}' is smaller "
                    f"({handle._shm.size}B) than requested ({byte_size}B)"
                )
            created = False
        handle._byte_size = byte_size
        _key_refcount[key] = _key_refcount.get(key, 0) + 1
        _active_regions.append(handle)
    rec = _observe._DATAPLANE
    if rec is not None:
        if created:
            rec.on_create("system", byte_size, key=id(handle))
        else:
            rec.on_attach("system", byte_size, key=id(handle))
    return handle


def set_shared_memory_region(
    shm_handle: SharedMemoryRegion, input_values, offset: int = 0
) -> None:
    """Copy each array in ``input_values`` into the region back-to-back."""
    if not isinstance(input_values, (list, tuple)):
        raise SharedMemoryException("input_values must be a list of numpy arrays")
    rec = _observe._DATAPLANE
    if rec is not None:
        rec.on_map("system", write=True)
    cursor = offset
    buf = shm_handle.buf()
    for value in input_values:
        arr = np.asarray(value)
        if arr.dtype == np.object_ or arr.dtype.kind in ("S", "U"):
            s = serialize_byte_tensor(arr)
            payload = s.item() if s.size else b""
        elif arr.dtype == np.dtype(triton_to_np_dtype("BF16")) and arr.dtype != np.float32:
            payload = serialize_bf16_tensor(arr).item()
        else:
            payload = np.ascontiguousarray(arr).tobytes()
        end = cursor + len(payload)
        if end > shm_handle.byte_size:
            raise SharedMemoryException(
                f"unable to set shared memory region: write of {len(payload)}B at "
                f"offset {cursor} exceeds region size {shm_handle.byte_size}B"
            )
        buf[cursor:end] = payload
        cursor = end


def get_contents_as_numpy(
    shm_handle: SharedMemoryRegion, datatype, shape, offset: int = 0
) -> np.ndarray:
    """A numpy view over the region (zero-copy for fixed-width dtypes).

    ``datatype`` may be a numpy dtype or a Triton datatype string.
    """
    rec = _observe._DATAPLANE
    if rec is not None:
        rec.on_map("system", write=False)
    if isinstance(datatype, str):
        np_dtype = np.dtype(triton_to_np_dtype(datatype))
        is_bytes = datatype == "BYTES"
    else:
        np_dtype = np.dtype(datatype)
        is_bytes = np_dtype == np.object_
    buf = shm_handle.buf()
    if is_bytes:
        from .. import deserialize_bytes_tensor

        n_elems = int(np.prod(shape)) if len(shape) else 1
        arr = deserialize_bytes_tensor(
            bytes(buf[offset : shm_handle.byte_size]), count=n_elems
        )
        return arr.reshape(shape)
    n_elems = int(np.prod(shape)) if len(shape) else 1
    nbytes = n_elems * np_dtype.itemsize
    if offset + nbytes > shm_handle.byte_size:
        raise SharedMemoryException(
            f"unable to read {nbytes}B at offset {offset} from region of "
            f"{shm_handle.byte_size}B"
        )
    return np.frombuffer(buf, dtype=np_dtype, count=n_elems, offset=offset).reshape(shape)


def mapped_shared_memory_regions() -> List[str]:
    """Names of regions currently mapped by this process."""
    with _lock:
        return [r.name for r in _active_regions]


def region_inventory() -> List[Dict[str, Any]]:
    """One dict per live handle (name/key/bytes) — the shm inventory the
    doctor snapshot reports alongside the data-plane counters."""
    with _lock:
        return [
            {"family": "system", "name": r.name, "key": r.key,
             "byte_size": r.byte_size}
            for r in _active_regions
        ]


def destroy_shared_memory_region(shm_handle: SharedMemoryRegion) -> None:
    """Unmap; unlink the underlying POSIX object when this is the last handle."""
    with _lock:
        if shm_handle._shm is None:
            return
        try:
            _active_regions.remove(shm_handle)
        except ValueError:
            pass
        key = shm_handle.key
        remaining = _key_refcount.get(key, 1) - 1
        if remaining <= 0:
            _key_refcount.pop(key, None)
            _owned_names.discard(_posix_name(key))
        else:
            _key_refcount[key] = remaining
        _safe_close(shm_handle._shm, unlink=remaining <= 0)
        shm_handle._shm = None
    rec = _observe._DATAPLANE
    if rec is not None:
        rec.on_destroy("system", shm_handle.byte_size, key=id(shm_handle))
