"""ctypes implementation of the DLPack ABI for shared-memory interop.

Lets a raw host window (a shared-memory region slice) act as a DLPack
*producer* so jax / torch / numpy can consume it zero-copy:
``np.from_dlpack(SharedMemoryTensor(...))`` or
``jax.dlpack.from_dlpack(...)``. Mirrors the role of the reference's
``tritonclient/utils/_dlpack.py`` (:57-270) and
``_shared_memory_tensor.py`` (:34-87) with an independent ctypes layout.
"""

from __future__ import annotations

import ctypes
from typing import Any, Optional, Sequence, Tuple

from . import InferenceServerException

_c_str_dltensor = b"dltensor"
_c_str_used_dltensor = b"used_dltensor"


class DLDevice(ctypes.Structure):
    _fields_ = [("device_type", ctypes.c_int32), ("device_id", ctypes.c_int32)]


class DLDataType(ctypes.Structure):
    _fields_ = [
        ("type_code", ctypes.c_uint8),
        ("bits", ctypes.c_uint8),
        ("lanes", ctypes.c_uint16),
    ]


class DLTensor(ctypes.Structure):
    _fields_ = [
        ("data", ctypes.c_void_p),
        ("device", DLDevice),
        ("ndim", ctypes.c_int32),
        ("dtype", DLDataType),
        ("shape", ctypes.POINTER(ctypes.c_int64)),
        ("strides", ctypes.POINTER(ctypes.c_int64)),
        ("byte_offset", ctypes.c_uint64),
    ]


class DLManagedTensor(ctypes.Structure):
    pass


_DELETER_TYPE = ctypes.CFUNCTYPE(None, ctypes.POINTER(DLManagedTensor))

DLManagedTensor._fields_ = [
    ("dl_tensor", DLTensor),
    ("manager_ctx", ctypes.c_void_p),
    ("deleter", _DELETER_TYPE),
]

# DLDeviceType values (dlpack.h)
kDLCPU = 1
kDLCUDA = 2

# DLDataTypeCode values
kDLInt = 0
kDLUInt = 1
kDLFloat = 2
kDLBfloat = 4
kDLBool = 6

_TRITON_TO_DL = {
    "BOOL": (kDLBool, 8),
    "INT8": (kDLInt, 8),
    "INT16": (kDLInt, 16),
    "INT32": (kDLInt, 32),
    "INT64": (kDLInt, 64),
    "UINT8": (kDLUInt, 8),
    "UINT16": (kDLUInt, 16),
    "UINT32": (kDLUInt, 32),
    "UINT64": (kDLUInt, 64),
    "FP16": (kDLFloat, 16),
    "FP32": (kDLFloat, 32),
    "FP64": (kDLFloat, 64),
    "BF16": (kDLBfloat, 16),
}


def triton_to_dlpack_dtype(dtype: str) -> DLDataType:
    entry = _TRITON_TO_DL.get(dtype)
    if entry is None:
        raise InferenceServerException(f"datatype '{dtype}' has no DLPack representation")
    code, bits = entry
    return DLDataType(code, bits, 1)


# Keep every exported manager alive until its deleter runs.
_live_managers: dict = {}


class _Manager:
    """Owns the ctypes storage for one exported DLManagedTensor."""

    def __init__(self, owner: Any, shape: Sequence[int]):
        self.owner = owner  # keeps the memory mapping alive
        n = len(shape)
        self.shape_arr = (ctypes.c_int64 * max(n, 1))(*([int(s) for s in shape] or [0]))
        self.managed = DLManagedTensor()

        def _deleter(ptr):
            _live_managers.pop(id(self), None)

        self._deleter_ref = _DELETER_TYPE(_deleter)


_pycapsule_new = ctypes.pythonapi.PyCapsule_New
_pycapsule_new.restype = ctypes.py_object
_pycapsule_new.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p]

_pycapsule_is_valid = ctypes.pythonapi.PyCapsule_IsValid
_pycapsule_is_valid.restype = ctypes.c_int
_pycapsule_is_valid.argtypes = [ctypes.py_object, ctypes.c_char_p]

_pycapsule_get_pointer = ctypes.pythonapi.PyCapsule_GetPointer
_pycapsule_get_pointer.restype = ctypes.c_void_p
_pycapsule_get_pointer.argtypes = [ctypes.py_object, ctypes.c_char_p]

# Raw-pointer variants for use inside the capsule destructor (separate PyDLL
# handle so the py_object argtypes above are untouched).
_capsule_api = ctypes.PyDLL(None)
_raw_is_valid = _capsule_api.PyCapsule_IsValid
_raw_is_valid.restype = ctypes.c_int
_raw_is_valid.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
_raw_get_pointer = _capsule_api.PyCapsule_GetPointer
_raw_get_pointer.restype = ctypes.c_void_p
_raw_get_pointer.argtypes = [ctypes.c_void_p, ctypes.c_char_p]

_PYCAPSULE_DESTRUCTOR = ctypes.CFUNCTYPE(None, ctypes.c_void_p)


@_PYCAPSULE_DESTRUCTOR
def _capsule_destructor(capsule_ptr):
    # DLPack contract: if the capsule is garbage-collected while still named
    # 'dltensor' (never consumed), the producer must invoke the deleter.
    try:
        if _raw_is_valid(capsule_ptr, _c_str_dltensor):
            ptr = _raw_get_pointer(capsule_ptr, _c_str_dltensor)
            if ptr:
                managed = ctypes.cast(ptr, ctypes.POINTER(DLManagedTensor))
                if managed.contents.deleter:
                    managed.contents.deleter(managed)
    except Exception:
        pass


def make_capsule(
    data_ptr: int,
    dtype: str,
    shape: Sequence[int],
    owner: Any,
    device: Tuple[int, int] = (kDLCPU, 0),
):
    """Build a 'dltensor' PyCapsule over raw contiguous memory at ``data_ptr``.

    ``owner`` is any object whose lifetime must cover the capsule's (e.g. the
    shared-memory mapping).
    """
    mgr = _Manager(owner, shape)
    t = mgr.managed.dl_tensor
    t.data = ctypes.c_void_p(data_ptr)
    t.device = DLDevice(device[0], device[1])
    t.ndim = len(shape)
    t.dtype = triton_to_dlpack_dtype(dtype)
    t.shape = ctypes.cast(mgr.shape_arr, ctypes.POINTER(ctypes.c_int64))
    t.strides = None  # NULL => compact row-major
    t.byte_offset = 0
    mgr.managed.manager_ctx = None
    mgr.managed.deleter = mgr._deleter_ref
    _live_managers[id(mgr)] = mgr
    return _pycapsule_new(
        ctypes.cast(ctypes.byref(mgr.managed), ctypes.c_void_p),
        _c_str_dltensor,
        ctypes.cast(_capsule_destructor, ctypes.c_void_p),
    )


def managed_tensor_from_capsule(capsule) -> DLManagedTensor:
    """Borrow the DLManagedTensor from a 'dltensor' capsule (for inspection)."""
    if not _pycapsule_is_valid(capsule, _c_str_dltensor):
        raise InferenceServerException("invalid or already-consumed dltensor capsule")
    ptr = _pycapsule_get_pointer(capsule, _c_str_dltensor)
    return ctypes.cast(ptr, ctypes.POINTER(DLManagedTensor)).contents


class SharedMemoryTensor:
    """DLPack producer over a slice of a host shared-memory region.

    Implements ``__dlpack__``/``__dlpack_device__`` so the region can be
    consumed directly by ``np.from_dlpack`` or ``jax.dlpack.from_dlpack``
    without copying the payload.
    """

    def __init__(
        self,
        data_ptr: int,
        dtype: str,
        shape: Sequence[int],
        owner: Any,
        device: Tuple[int, int] = (kDLCPU, 0),
    ):
        self._data_ptr = data_ptr
        self._dtype = dtype
        self._shape = list(shape)
        self._owner = owner
        self._device = device

    def __dlpack__(self, stream: Optional[int] = None, **kwargs):
        return make_capsule(
            self._data_ptr, self._dtype, self._shape, self._owner, self._device
        )

    def __dlpack_device__(self) -> Tuple[int, int]:
        return self._device

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self._shape)

    @property
    def triton_dtype(self) -> str:
        return self._dtype
