"""Triton/KServe-v2 dtype mapping and tensor wire serialization.

Semantics-parity rebuild of the reference's
``src/python/library/tritonclient/utils/__init__.py`` (dtype maps :148-205,
BYTES wire format :208-291, BF16 :294-363, exception :86-145), re-designed
TPU-first:

- BF16 is a *native* dtype here (``ml_dtypes.bfloat16``), not a float32
  stand-in: ``triton_to_np_dtype("BF16")`` returns ``ml_dtypes.bfloat16`` and
  BF16 wire payloads deserialize zero-copy as bfloat16 arrays. The reference
  round-trips BF16 through float32 truncation because numpy-on-CUDA-host has
  no bf16; on a TPU stack bf16 is the working dtype.
- Serializers accept anything with ``__array__`` (numpy, jax.Array already on
  host, torch CPU tensors).

Wire formats (identical to the reference so payloads interoperate with a real
tritonserver):

- BYTES tensor: each element is a 4-byte little-endian length prefix followed
  by the raw bytes, elements concatenated in C (row-major) order.
- BF16 tensor: 2 bytes per element, little-endian, i.e. the raw bits of
  bfloat16.
"""

from __future__ import annotations

import struct
from typing import Any, List, Optional

import numpy as np

try:  # ml_dtypes ships with jax; guard anyway so this module is pure-numpy safe
    import ml_dtypes

    _BFLOAT16 = np.dtype(ml_dtypes.bfloat16)
except Exception:  # pragma: no cover - ml_dtypes is present in this environment
    ml_dtypes = None
    _BFLOAT16 = None


# Request parameter names reserved by the protocol: users may not pass these
# through the custom-parameters bag (reference utils/__init__.py:39-48).
RESERVED_REQUEST_PARAMETERS = frozenset(
    (
        "sequence_id",
        "sequence_start",
        "sequence_end",
        "priority",
        "binary_data_output",
    )
)


class InferenceServerException(Exception):
    """Exception carrying a message plus optional HTTP/GRPC status and debug detail."""

    def __init__(self, msg: str, status: Optional[str] = None, debug_details: Any = None):
        super().__init__(msg)
        self._msg = msg
        self._status = status
        self._debug_details = debug_details

    def __str__(self) -> str:
        out = self._msg if self._msg is not None else ""
        if self._status is not None:
            out = "[" + self._status + "] " + out
        return out

    def message(self) -> Optional[str]:
        return self._msg

    def status(self) -> Optional[str]:
        return self._status

    def debug_details(self) -> Any:
        return self._debug_details


def sorted_percentile(sorted_values, q: float) -> float:
    """The q-quantile of an ascending sequence by the index convention
    every harness/stats surface in this repo shares (min(int(n*q), n-1));
    0.0 when empty. Callers sort once and take several quantiles."""
    if not sorted_values:
        return 0.0
    idx = min(int(len(sorted_values) * q), len(sorted_values) - 1)
    return sorted_values[idx]


def raise_error(msg: str) -> "NoReturn":  # noqa: F821
    """Raise an InferenceServerException with ``msg`` (helper for examples/tests)."""
    raise InferenceServerException(msg=msg)


# ---------------------------------------------------------------------------
# dtype maps
# ---------------------------------------------------------------------------

_NP_TO_TRITON = {
    np.dtype(np.bool_): "BOOL",
    np.dtype(np.int8): "INT8",
    np.dtype(np.int16): "INT16",
    np.dtype(np.int32): "INT32",
    np.dtype(np.int64): "INT64",
    np.dtype(np.uint8): "UINT8",
    np.dtype(np.uint16): "UINT16",
    np.dtype(np.uint32): "UINT32",
    np.dtype(np.uint64): "UINT64",
    np.dtype(np.float16): "FP16",
    np.dtype(np.float32): "FP32",
    np.dtype(np.float64): "FP64",
    np.dtype(np.object_): "BYTES",
}
if _BFLOAT16 is not None:
    _NP_TO_TRITON[_BFLOAT16] = "BF16"

_TRITON_TO_NP = {
    "BOOL": np.bool_,
    "INT8": np.int8,
    "INT16": np.int16,
    "INT32": np.int32,
    "INT64": np.int64,
    "UINT8": np.uint8,
    "UINT16": np.uint16,
    "UINT32": np.uint32,
    "UINT64": np.uint64,
    "FP16": np.float16,
    "FP32": np.float32,
    "FP64": np.float64,
    "BYTES": np.object_,
    "BF16": (_BFLOAT16 if _BFLOAT16 is not None else np.float32),
}

# Size in bytes of one element on the wire; BYTES is variable (None).
_TRITON_DTYPE_SIZES = {
    "BOOL": 1,
    "INT8": 1,
    "INT16": 2,
    "INT32": 4,
    "INT64": 8,
    "UINT8": 1,
    "UINT16": 2,
    "UINT32": 4,
    "UINT64": 8,
    "FP16": 2,
    "FP32": 4,
    "FP64": 8,
    "BF16": 2,
    "BYTES": None,
}


def np_to_triton_dtype(np_dtype) -> Optional[str]:
    """Map a numpy dtype (or dtype-like) to the Triton datatype string."""
    dt = np.dtype(np_dtype)
    if dt.kind in ("S", "U"):
        return "BYTES"
    return _NP_TO_TRITON.get(dt)


def triton_to_np_dtype(dtype: str):
    """Map a Triton datatype string to a numpy dtype.

    BF16 maps to ``ml_dtypes.bfloat16`` (TPU-native divergence from the
    reference, which maps it to float32).
    """
    return _TRITON_TO_NP.get(dtype)


def triton_dtype_element_size(dtype: str) -> Optional[int]:
    """Bytes per element on the wire for ``dtype``; None for BYTES (variable)."""
    return _TRITON_DTYPE_SIZES.get(dtype)


def serialized_byte_size(np_array: np.ndarray) -> int:
    """Byte size this array will occupy on the wire."""
    if np_array.dtype == np.object_ or np_array.dtype.kind in ("S", "U"):
        serialized = serialize_byte_tensor(np_array)
        return len(serialized.item()) if serialized.size > 0 else 0
    return np_array.nbytes


# ---------------------------------------------------------------------------
# BYTES tensors
# ---------------------------------------------------------------------------


def _element_to_bytes(obj: Any) -> bytes:
    if isinstance(obj, (bytes, bytearray, memoryview)):
        return bytes(obj)
    if isinstance(obj, str):
        return obj.encode("utf-8")
    if isinstance(obj, np.bytes_):
        return bytes(obj)
    # numpy str scalar, numbers, etc.
    return str(obj).encode("utf-8")


def serialize_byte_tensor(input_tensor) -> np.ndarray:
    """Serialize a BYTES tensor to the 4-byte-LE-length-prefixed wire format.

    Accepts object/str/bytes numpy arrays. Returns a 1-element object ndarray
    whose ``.item()`` is the serialized buffer (matching the reference's
    calling convention), or an empty array if the tensor has no elements.
    """
    arr = np.asarray(input_tensor)
    if arr.size == 0:
        return np.empty([0], dtype=np.object_)
    if not (arr.dtype == np.object_ or arr.dtype.kind in ("S", "U")):
        raise_error("cannot serialize bytes tensor: invalid datatype")
    chunks: List[bytes] = []
    for obj in np.nditer(arr, flags=["refs_ok"], order="C"):
        item = _element_to_bytes(obj.item())
        chunks.append(struct.pack("<I", len(item)))
        chunks.append(item)
    out = np.empty([1], dtype=np.object_)
    out[0] = b"".join(chunks)
    return out


def deserialize_bytes_tensor(encoded_tensor: bytes, count: Optional[int] = None) -> np.ndarray:
    """Deserialize a BYTES wire payload to a flat object ndarray of ``bytes``.

    ``count`` bounds the number of elements (used when reading from a region
    larger than the payload, e.g. shared memory)."""
    strs: List[bytes] = []
    buf = memoryview(encoded_tensor)
    offset = 0
    n = len(buf)
    while offset < n and (count is None or len(strs) < count):
        if offset + 4 > n:
            raise InferenceServerException(
                "malformed BYTES tensor: truncated length prefix"
            )
        (length,) = struct.unpack_from("<I", buf, offset)
        offset += 4
        if offset + length > n:
            raise InferenceServerException("malformed BYTES tensor: truncated element")
        strs.append(bytes(buf[offset : offset + length]))
        offset += length
    return np.array(strs, dtype=np.object_)


# ---------------------------------------------------------------------------
# BF16 tensors
# ---------------------------------------------------------------------------


def serialize_bf16_tensor(input_tensor) -> np.ndarray:
    """Serialize a tensor to BF16 wire format (2 bytes/element, LE).

    Accepts bfloat16 arrays (zero-conversion fast path), or any float array
    (converted with round-to-nearest-even — a strict accuracy improvement over
    the reference's bit-truncation).

    Returns a 1-element object ndarray whose ``.item()`` is the buffer.
    """
    arr = np.asarray(input_tensor)
    if arr.size == 0:
        return np.empty([0], dtype=np.object_)
    if _BFLOAT16 is None:
        raise_error("bfloat16 support requires ml_dtypes")
    if arr.dtype != _BFLOAT16:
        arr = arr.astype(_BFLOAT16)
    out = np.empty([1], dtype=np.object_)
    out[0] = np.ascontiguousarray(arr).tobytes()
    return out


def deserialize_bf16_tensor(encoded_tensor: bytes) -> np.ndarray:
    """Deserialize a BF16 wire payload to a flat bfloat16 ndarray (zero-copy)."""
    if _BFLOAT16 is None:
        return np.frombuffer(encoded_tensor, dtype=np.uint16).astype(np.float32)
    return np.frombuffer(encoded_tensor, dtype=_BFLOAT16)
