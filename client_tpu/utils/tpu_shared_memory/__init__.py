"""TPU shared memory: the zero-copy device data plane.

This module is the TPU-native replacement for the reference's
``tritonclient.utils.cuda_shared_memory`` (cuda_shared_memory/__init__.py:
create :107-149, get_raw_handle :152-170, set :173-239, DLPack set :328-388,
as_shared_memory_tensor :391-399, get :242-325, destroy :414-429), with the
same function-for-function API so shm-mode tooling slots in unchanged.

Design — why it is not a CUDA-IPC translation:

- CUDA shm regions are ``cudaMalloc`` buffers exported cross-process via
  ``cudaIpcGetMemHandle``. TPU/XLA has no device-memory IPC: device buffers
  are owned by the XLA runtime and are not exportable between processes.
- A region here is therefore a **host-pinned window + device-entry cache**:
  the host window is a POSIX shm mapping (cross-process transport, DMA-able
  by a co-located server), and the cache pins live ``jax.Array`` device
  buffers keyed by region offset.
- **Same process** (our in-process server, or any runtime embedding both
  client and server): a device-cached tensor is handed over as the actual
  ``jax.Array`` — zero copies, the accelerator buffer itself crosses the API.
- **Cross process**: the raw handle (base64 JSON descriptor, the analogue of
  the base64'd ``cudaIpcMemHandle``) carries the host window's shm key; the
  peer attaches the window and the transfer is one DMA hop each way
  (device->window, window->device) instead of a wire serialization.
- ``colocated=True`` regions skip host mirroring on device writes: when both
  ends share the process, tensors never leave HBM at all.

jax's async dispatch replaces cudashm's per-device stream cache
(:62-70): ``device_put`` returns immediately; fences are taken only at host
reads (``np.asarray``) exactly where cudashm synchronized its stream.
"""

from __future__ import annotations

import base64
import json
import threading
import uuid as _uuid
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import (
    InferenceServerException,
    np_to_triton_dtype,
    serialize_bf16_tensor,
    serialize_byte_tensor,
    triton_to_np_dtype,
)
from ... import observe as _observe
from .._dlpack import SharedMemoryTensor, kDLCPU
from ..shared_memory import (
    SharedMemoryException,
    _safe_close,
    attach_shared_memory,
)


def _record_map(write: bool) -> None:
    # data-plane accounting: one op per public map-level call; with no
    # recorder installed this is one attribute load + None check
    rec = _observe._DATAPLANE
    if rec is not None:
        rec.on_map("tpu", write)


def _is_jax_array(t: Any) -> bool:
    mod = type(t).__module__
    return mod.startswith("jax") or mod.startswith("jaxlib")


def _as_u8(arr) -> np.ndarray:
    """Flat uint8 view of any host array (handles bfloat16 and friends)."""
    return np.ascontiguousarray(arr).view(np.uint8).reshape(-1)


class TpuSharedMemoryRegion:
    """A TPU shared-memory region: host window + device-entry cache."""

    def __init__(
        self,
        triton_shm_name: str,
        shm_key: str,
        byte_size: int,
        device_id: int = 0,
        colocated: bool = False,
    ):
        self._triton_shm_name = triton_shm_name
        self._shm_key = shm_key
        self._byte_size = byte_size
        self._device_id = device_id
        self._colocated = colocated
        self._uuid = _uuid.uuid4().hex
        self._shm = None
        # False for cross-process attachments: another process can mutate the
        # host window invisibly, so pinned device entries must not be trusted
        # (and caching writes would be pointless — no in-process reader).
        self._cache_enabled = True
        # offset -> (jax.Array, nbytes); authoritative over the host window
        # for its byte range until flushed or overwritten.
        self._device_entries: Dict[int, Tuple[Any, int]] = {}
        self._lock = threading.RLock()

    # -- accessors ---------------------------------------------------------
    @property
    def name(self) -> str:
        return self._triton_shm_name

    @property
    def shm_key(self) -> str:
        return self._shm_key

    @property
    def byte_size(self) -> int:
        return self._byte_size

    @property
    def device_id(self) -> int:
        return self._device_id

    @property
    def colocated(self) -> bool:
        return self._colocated

    def device(self):
        import jax

        devices = jax.devices()
        if self._device_id >= len(devices):
            raise SharedMemoryException(
                f"device_id {self._device_id} out of range ({len(devices)} devices)"
            )
        return devices[self._device_id]

    def _host_buf(self) -> memoryview:
        if self._shm is None:
            raise SharedMemoryException(
                f"tpu shared-memory region '{self._triton_shm_name}' is not mapped"
            )
        return self._shm.buf

    def host_buffer(self) -> memoryview:
        """The raw mapped host window (public twin of the system regions'
        ``buf()``). NOTE: does NOT flush cached device entries — callers
        slicing sub-ranges (the arena's slab views) flush via
        :meth:`read_host`/``_flush_overlapping`` first, or use
        :meth:`read_host` for a coherent view."""
        return self._host_buf()

    def _check(self, nbytes: int, offset: int, op: str) -> None:
        if offset < 0 or nbytes < 0 or offset + nbytes > self._byte_size:
            raise SharedMemoryException(
                f"tpu shared-memory {op} of {nbytes}B at offset {offset} exceeds "
                f"region '{self._triton_shm_name}' ({self._byte_size}B)"
            )

    # -- device-entry cache ------------------------------------------------
    def _invalidate_overlapping(self, offset: int, nbytes: int) -> None:
        with self._lock:
            for off, (_, n) in list(self._device_entries.items()):
                if off < offset + nbytes and offset < off + n:
                    del self._device_entries[off]

    def _flush_overlapping(self, offset: int, nbytes: int) -> None:
        """Materialize overlapping device entries into the host window."""
        with self._lock:
            for off, (arr, n) in list(self._device_entries.items()):
                if off < offset + nbytes and offset < off + n:
                    host = np.asarray(arr)  # D2H fence
                    self._host_buf()[off : off + n] = _as_u8(host)[:n]
                    del self._device_entries[off]

    def _cache_device_entry(self, offset: int, arr: Any, nbytes: int) -> None:
        if not self._cache_enabled:
            return
        with self._lock:
            self._invalidate_overlapping(offset, nbytes)
            self._device_entries[offset] = (arr, nbytes)

    def _device_entry(self, offset: int, nbytes: int):
        if not self._cache_enabled:
            return None
        with self._lock:
            hit = self._device_entries.get(offset)
            if hit is not None and hit[1] == nbytes:
                return hit[0]
        return None

    # -- host paths (used by servers and byte-level access) ----------------
    def read_host(self, byte_size: int, offset: int = 0) -> memoryview:
        self._check(byte_size, offset, "read")
        self._flush_overlapping(offset, byte_size)
        return self._host_buf()[offset : offset + byte_size]

    def write_host(self, data, offset: int = 0) -> None:
        data = memoryview(data).cast("B")
        self._check(len(data), offset, "write")
        self._invalidate_overlapping(offset, len(data))
        self._host_buf()[offset : offset + len(data)] = data

    def detach(self) -> None:
        """Release a cross-process attachment (no-op for owned/in-process
        regions, whose lifetime belongs to their creator)."""
        if not self._cache_enabled and self._shm is not None:
            _safe_close(self._shm, unlink=False)
            self._shm = None
            rec = _observe._DATAPLANE
            if rec is not None:  # residency ended: account like a destroy
                rec.on_destroy("tpu", self._byte_size, key=id(self))

    def host_address(self, offset: int = 0) -> int:
        """Raw address of the host window at ``offset`` (for DLPack export)."""
        import ctypes

        buf = self._host_buf()
        addr = ctypes.addressof(ctypes.c_char.from_buffer(buf))
        return addr + offset


# Process-global registry: in-process attach resolves to the same region
# object, which is what makes the zero-copy device handover possible.
_lock = threading.Lock()
_registry: Dict[str, TpuSharedMemoryRegion] = {}


def allocated_shared_memory_regions() -> List[str]:
    with _lock:
        return [r.name for r in _registry.values()]


def region_inventory() -> List[Dict[str, Any]]:
    """One dict per region allocated by this process (doctor inventory)."""
    with _lock:
        regions = list(_registry.values())
    return [
        {"family": "tpu", "name": r.name, "key": r.shm_key,
         "byte_size": r.byte_size, "device_id": r.device_id,
         "colocated": r.colocated,
         "device_entries": len(r._device_entries)}
        for r in regions
    ]


def create_shared_memory_region(
    triton_shm_name: str,
    byte_size: int,
    device_id: int = 0,
    colocated: bool = False,
    key: Optional[str] = None,
) -> TpuSharedMemoryRegion:
    """Allocate a region: a POSIX host window bound to TPU ``device_id``.

    ``colocated=True`` promises that producer and consumer share this
    process; device writes then skip host mirroring and tensors stay in HBM.
    """
    from multiprocessing import shared_memory as mpshm

    if byte_size <= 0:
        raise SharedMemoryException("tpu shared-memory byte_size must be positive")
    shm_key = key or f"tpushm_{_uuid.uuid4().hex[:12]}"
    region = TpuSharedMemoryRegion(triton_shm_name, shm_key, byte_size, device_id, colocated)
    try:
        region._shm = mpshm.SharedMemory(name=shm_key, create=True, size=byte_size)
        from ..shared_memory import _owned_names, _posix_name

        _owned_names.add(_posix_name(shm_key))
    except FileExistsError:
        raise SharedMemoryException(
            f"unable to create tpu shared-memory region: key '{shm_key}' exists"
        )
    with _lock:
        _registry[shm_key] = region
    rec = _observe._DATAPLANE
    if rec is not None:
        rec.on_create("tpu", byte_size, key=id(region))
    return region


def get_raw_handle(shm_handle: TpuSharedMemoryRegion) -> str:
    """Serializable descriptor (base64 JSON) — the cudaIpcMemHandle analogue."""
    desc = {
        "kind": "tpu_shared_memory",
        "shm_key": shm_handle.shm_key,
        "byte_size": shm_handle.byte_size,
        "device_id": shm_handle.device_id,
        "uuid": shm_handle._uuid,
        "colocated": shm_handle.colocated,
    }
    return base64.b64encode(json.dumps(desc).encode("utf-8")).decode("ascii")


def attach_from_raw_handle(raw_handle: str) -> TpuSharedMemoryRegion:
    """Attach to a region from its raw handle.

    Same process: returns the *original* region object (device cache and all).
    Other process: maps the host window read/write.
    """
    try:
        desc = json.loads(base64.b64decode(raw_handle))
        shm_key = desc["shm_key"]
    except Exception as e:
        raise SharedMemoryException(f"invalid tpu shared-memory raw handle: {e}")
    with _lock:
        existing = _registry.get(shm_key)
    if existing is not None:
        return existing
    region = TpuSharedMemoryRegion(
        desc.get("name", shm_key),
        shm_key,
        int(desc["byte_size"]),
        int(desc.get("device_id", 0)),
        bool(desc.get("colocated", False)),
    )
    region._cache_enabled = False  # cross-process: host window is truth
    try:
        region._shm = attach_shared_memory(shm_key)
    except FileNotFoundError:
        raise SharedMemoryException(
            f"unable to attach tpu shared-memory region with key '{shm_key}'"
        )
    rec = _observe._DATAPLANE
    if rec is not None:
        rec.on_attach("tpu", region.byte_size, key=id(region))
    return region


def set_shared_memory_region(
    shm_handle: TpuSharedMemoryRegion, input_values, offset: int = 0
) -> None:
    """Copy host arrays into the region back-to-back (BYTES/BF16-aware).

    jax.Arrays are accepted and routed through the device cache instead
    (keeping the device buffer live and mirroring to host unless colocated).
    """
    if not isinstance(input_values, (list, tuple)):
        raise SharedMemoryException("input_values must be a list of arrays")
    _record_map(write=True)
    cursor = offset
    for value in input_values:
        if _is_jax_array(value):
            cursor = _set_from_jax(shm_handle, value, cursor)
            continue
        arr = np.asarray(value)
        if arr.dtype == np.object_ or arr.dtype.kind in ("S", "U"):
            s = serialize_byte_tensor(arr)
            payload = memoryview(s.item() if s.size else b"")
        else:
            payload = _as_u8(arr)
        shm_handle.write_host(payload, cursor)
        cursor += len(payload)


def set_shared_memory_region_from_jax(
    shm_handle: TpuSharedMemoryRegion, array, offset: int = 0, timers=None
) -> int:
    """Bind a jax.Array into the region at ``offset``; returns the end offset.

    The device buffer is pinned in the region's cache (in-process consumers
    get it back with zero copies). Unless the region is colocated, the bytes
    are also mirrored into the host window for cross-process consumers —
    one D2H DMA, the same hop cudashm pays in ``cudaMemcpyAsync``.

    ``timers``: optional :class:`client_tpu._base.RequestTimers`; when the
    host mirror actually runs, its D2H_START/D2H_END points are captured
    (direction semantics: device HBM -> host window).
    """
    _record_map(write=True)
    return _set_from_jax(shm_handle, array, offset, timers)


def _set_from_jax(shm_handle, array, offset=0, timers=None) -> int:
    nbytes = array.dtype.itemsize * array.size
    shm_handle._check(nbytes, offset, "write")
    shm_handle._cache_device_entry(offset, array, nbytes)
    if not shm_handle.colocated or not shm_handle._cache_enabled:
        if timers is not None:
            timers.capture("D2H_START")
        shm_handle._host_buf()[offset : offset + nbytes] = _as_u8(np.asarray(array))[:nbytes]
        if timers is not None:
            timers.capture("D2H_END")
    return offset + nbytes


def set_shared_memory_region_from_dlpack(
    shm_handle: TpuSharedMemoryRegion, tensor, offset: int = 0
) -> None:
    """Ingest any ``__dlpack__`` producer (torch/numpy host tensors, jax)."""
    _record_map(write=True)
    if _is_jax_array(tensor):
        _set_from_jax(shm_handle, tensor, offset)
        return
    try:
        arr = np.from_dlpack(tensor)
    except Exception as e:
        raise SharedMemoryException(f"cannot consume dlpack tensor: {e}")
    shm_handle.write_host(memoryview(np.ascontiguousarray(arr)).cast("B"), offset)


def get_contents_as_numpy(
    shm_handle: TpuSharedMemoryRegion, datatype, shape, offset: int = 0
) -> np.ndarray:
    """Host view of the region contents (flushes device entries first)."""
    _record_map(write=False)
    if isinstance(datatype, str):
        triton_dtype = datatype
    else:
        triton_dtype = np_to_triton_dtype(np.dtype(datatype))
    if triton_dtype == "BYTES":
        from .. import deserialize_bytes_tensor

        n_elems = int(np.prod(shape)) if len(shape) else 1
        raw = shm_handle.read_host(shm_handle.byte_size - offset, offset)
        return deserialize_bytes_tensor(bytes(raw), count=n_elems).reshape(shape)
    np_dtype = np.dtype(triton_to_np_dtype(triton_dtype))
    n_elems = int(np.prod(shape)) if len(shape) else 1
    nbytes = n_elems * np_dtype.itemsize
    raw = shm_handle.read_host(nbytes, offset)
    return np.frombuffer(raw, dtype=np_dtype, count=n_elems).reshape(shape)


def get_contents_as_jax(
    shm_handle: TpuSharedMemoryRegion, datatype, shape, offset: int = 0, timers=None
):
    """Device view of the region contents.

    Cache hit (the producer was a jax.Array in this process): returns the
    pinned device array — zero copies. Otherwise: one async H2D
    ``device_put`` from the host window; with ``timers`` given, its
    H2D_START/H2D_END points bracket that transfer (to completion).
    """
    _record_map(write=False)
    import jax

    if isinstance(datatype, str):
        np_dtype = np.dtype(triton_to_np_dtype(datatype))
    else:
        np_dtype = np.dtype(datatype)
    n_elems = int(np.prod(shape)) if len(shape) else 1
    nbytes = n_elems * np_dtype.itemsize
    shm_handle._check(nbytes, offset, "read")
    cached = shm_handle._device_entry(offset, nbytes)
    if cached is not None and cached.dtype == np_dtype:
        return cached.reshape(shape)
    host = np.frombuffer(
        shm_handle.read_host(nbytes, offset), dtype=np_dtype, count=n_elems
    ).reshape(shape)
    if timers is None:
        return jax.device_put(host, shm_handle.device())
    timers.capture("H2D_START")
    out = jax.device_put(host, shm_handle.device())
    out.block_until_ready()
    timers.capture("H2D_END")
    return out


def as_shared_memory_tensor(
    shm_handle: TpuSharedMemoryRegion, datatype: str, shape: Sequence[int], offset: int = 0
) -> SharedMemoryTensor:
    """Expose the host window as a DLPack producer (zero-copy consumers)."""
    _record_map(write=False)
    np_dtype = np.dtype(triton_to_np_dtype(datatype))
    n_elems = int(np.prod(shape)) if len(shape) else 1
    nbytes = n_elems * np_dtype.itemsize
    shm_handle._check(nbytes, offset, "read")
    shm_handle._flush_overlapping(offset, nbytes)
    return SharedMemoryTensor(
        shm_handle.host_address(offset), datatype, shape, owner=shm_handle,
        device=(kDLCPU, 0),
    )


def destroy_shared_memory_region(shm_handle: TpuSharedMemoryRegion) -> None:
    """Drop device entries, unmap the window, unlink if we created it."""
    with _lock:
        owned = _registry.pop(shm_handle.shm_key, None) is not None
    with shm_handle._lock:
        shm_handle._device_entries.clear()
    if shm_handle._shm is not None:
        if owned:
            from ..shared_memory import _owned_names, _posix_name

            _owned_names.discard(_posix_name(shm_handle.shm_key))
        _safe_close(shm_handle._shm, unlink=owned)
        shm_handle._shm = None
        rec = _observe._DATAPLANE
        if rec is not None:
            rec.on_destroy("tpu", shm_handle.byte_size, key=id(shm_handle))
