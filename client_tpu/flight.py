"""Flight recorder: tail-based retention of per-request causal timelines.

The aggregate telemetry (``client_tpu.observe``) can say *that* the p999
burned an SLO; nothing in the process can say *why request X was slow* —
which retry fired, which endpoint was re-homed, whether the token parked
in an admission queue or a coalescing window, whether the cache stale
path refreshed. This module is the per-request attribution layer every
other layer reports into:

- Every layer emits **structured point events** into a thread/task-local
  scratch buffer via :func:`note` — a plain list append keyed off one
  contextvar, ~sub-microsecond per event, and exactly one branch when no
  request is being recorded (the contextvar reads ``None``).
- The **outermost** layer of a request (cache -> batch -> pool ->
  endpoint frontend, whichever the caller holds) opens the scratch with
  :meth:`FlightRecorder.begin` and settles it with
  :meth:`FlightRecorder.commit`; nested layers see an active scratch and
  only append. Events across layers therefore land on ONE timeline in
  causal order, stitched to the wire via the W3C trace ids of every
  endpoint span begun under the scratch (``span``-layer events).
- **Tail-based retention** is the headline mechanism: at commit a
  *verdict* decides whether the whole timeline is retained in a bounded
  ring or dropped wholesale — ``error`` (the request failed), ``shed``
  (admission/breaker shed it), ``slo_breach`` (over the declared
  ``slo_ms``), ``slow`` (over a rolling tail-quantile threshold of
  recent durations), or ``baseline`` (a small reservoir sample of
  healthy traffic for contrast). Fast healthy requests — the
  overwhelming majority at production rates — cost one scratch list
  that is dropped whole; full forensic detail exists for exactly the
  requests worth explaining.
- Exporters: :meth:`FlightRecorder.to_chrome_trace` (merged with the
  tracer ring's ``RequestSpan`` phase intervals by trace id),
  :meth:`FlightRecorder.dump_jsonl`, and
  :meth:`FlightRecorder.last_anomalies`;
  :meth:`FlightRecorder.tail_divergence` is the anomaly detector behind
  the doctor's ``tail_divergence`` flag, and
  ``client_tpu.doctor --postmortem`` packages the retained timelines
  with the fleet snapshot into one self-contained bundle.

Wiring: ``Telemetry(flight=FlightRecorder())`` (or ``flight=True``)
arms it; the frontends, pool, admission, batching, cache, arena, shard
and federation layers all emit automatically (the federation layer
stamps every event with its ``cell`` and contributes ``route`` /
``cell_spill`` / ``spill_engaged``/``spill_released`` /
``canary_route``/``canary_rollback`` / ``shadow_mirror``/
``shadow_diverged`` / ``sequence_abandoned`` — a divergent shadow
response is retained on its OWN timeline). See docs/observability.md
"Flight recorder & postmortems".
"""

from __future__ import annotations

import contextvars
import itertools
import json
import random
import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "FLIGHT_VERDICTS",
    "FlightRecorder",
    "FlightTimeline",
    "active_scratch",
    "layer_begin",
    "layer_commit",
    "note",
]

# retained-timeline verdicts, roughly most-severe first. "disrupted" is
# the stream-specific verdict (the stream reconnected mid-flight but
# finished); "baseline" is the healthy-contrast reservoir sample; "mark"
# is an out-of-band marker timeline (the watchtower's ``watch.alert``
# edges land in the ring this way — requestless, but retained so a
# postmortem reads alerts interleaved with the requests they explain).
FLIGHT_VERDICTS = (
    "error", "shed", "slo_breach", "slow", "disrupted", "baseline", "mark")

# The active scratch for the request being processed on this thread/task.
# contextvars give thread- AND asyncio-task-locality in one mechanism;
# executor threads (hedge attempts, shard fan-out workers) do not inherit
# the caller's context, so their note() calls are no-ops unless an
# endpoint span opens its own scratch there — exactly the isolation the
# coordinator-side events (hedge launch/win, shard dispatch) rely on.
_SCRATCH: contextvars.ContextVar = contextvars.ContextVar(
    "client_tpu_flight_scratch", default=None)


class _Scratch:
    """One in-progress request's append-only event buffer. Never shared
    across threads: it lives in exactly one context between begin() and
    commit()."""

    __slots__ = ("start_ns", "frontend", "model", "op", "events",
                 "truncated", "trace_id", "trace_ids", "limit", "token",
                 "committed")

    def __init__(self, frontend: str, model: str, op: str, limit: int):
        self.start_ns = time.perf_counter_ns()
        self.frontend = frontend
        self.model = model
        self.op = op
        # (perf_counter_ns, layer, event, attrs-or-None) tuples
        self.events: List[Tuple[int, str, str, Optional[dict]]] = []
        self.truncated = 0
        self.trace_id: Optional[str] = None
        self.trace_ids: List[str] = []
        self.limit = limit
        self.token = None
        self.committed = False

    def append(self, layer: str, event: str, **attrs) -> None:
        """Cap-aware append for callers that already HOLD the scratch
        (:func:`note` inlines the same rule for the contextvar hot path —
        keep the two in sync)."""
        if len(self.events) < self.limit:
            self.events.append((time.perf_counter_ns(), layer, event,
                                attrs or None))
        else:
            self.truncated += 1


def note(layer: str, event: str, **attrs) -> None:
    """Record one structured event on the active request's timeline.

    THE hot-path entry every layer calls unconditionally: with no request
    being recorded (no recorder armed, or this thread/task is outside a
    request) the contextvar reads None and this is one branch. With an
    active scratch it is one ``perf_counter_ns`` plus a bounded list
    append — the committed per-event cost in BENCH_FLIGHT.json. The
    cap-and-append rule is inlined for speed: keep it in sync with
    :meth:`_Scratch.append`."""
    s = _SCRATCH.get()
    if s is None or s.committed:
        # committed guard: a task that inherited a context COPY (aio
        # batch flusher, hedge task) may still see a scratch its owner
        # already settled — its events list now belongs to a retained
        # timeline and must never grow
        return
    if len(s.events) < s.limit:
        s.events.append((time.perf_counter_ns(), layer, event,
                         attrs or None))
    else:
        s.truncated += 1


def active_scratch() -> Optional[_Scratch]:
    """The in-progress scratch on this context, if any (introspection)."""
    return _SCRATCH.get()


def layer_begin(telemetry, frontend: str, model: str,
                op: str = "infer") -> Optional[_Scratch]:
    """The wrapper layers' (pool/batch/cache/shard) one-line gate: open a
    scratch owned by this layer, or None when no recorder is armed on
    ``telemetry`` or a request is already being recorded (nested layer)."""
    if telemetry is None:
        return None
    recorder = getattr(telemetry, "flight", None)
    if recorder is None:
        return None
    return recorder.begin(frontend, model, op)


def layer_commit(telemetry, scratch: Optional[_Scratch],
                 error: Optional[BaseException] = None) -> None:
    """Settle a scratch opened by :func:`layer_begin` (no-op for None)."""
    if scratch is not None:
        telemetry.flight.commit(scratch, error=error)


class _RollingQuantile:
    """A rolling tail-quantile threshold over the last ``window``
    durations, recomputed every ``refresh`` insertions (a sort of a
    bounded copy, amortized off the per-request path). Returns None until
    ``min_samples`` durations have been observed — the recorder samples
    nothing as "slow" before it knows what normal looks like."""

    __slots__ = ("quantile", "window", "refresh", "min_samples", "_buf",
                 "_idx", "_count", "_since", "_value")

    def __init__(self, quantile: float = 0.99, window: int = 2048,
                 refresh: int = 256, min_samples: int = 128):
        if not 0.0 < quantile < 1.0:
            raise ValueError("quantile must be in (0, 1)")
        self.quantile = quantile
        self.window = int(window)
        self.refresh = max(1, int(refresh))
        self.min_samples = max(1, int(min_samples))
        self._buf: List[float] = []
        self._idx = 0
        self._count = 0
        self._since = 0
        self._value: Optional[float] = None

    def add(self, value: float) -> None:
        if len(self._buf) < self.window:
            self._buf.append(value)
        else:
            self._buf[self._idx] = value
            self._idx = (self._idx + 1) % self.window
        self._count += 1
        self._since += 1
        if self._since >= self.refresh or (
                self._value is None and self._count >= self.min_samples):
            self._since = 0
            if self._count >= self.min_samples:
                s = sorted(self._buf)
                from .utils import sorted_percentile

                self._value = sorted_percentile(s, self.quantile)

    def threshold(self) -> Optional[float]:
        return self._value


class FlightTimeline:
    """One committed (retained) request timeline: immutable after commit."""

    __slots__ = ("seq", "verdict", "trace_id", "trace_ids", "frontend",
                 "model", "op", "start_ns", "end_ns", "duration_ms",
                 "error", "events", "truncated", "_attribution")

    def __init__(self, seq: int, verdict: str, scratch: _Scratch,
                 end_ns: int, error: Optional[str]):
        self.seq = seq
        self.verdict = verdict
        self.trace_id = scratch.trace_id
        self.trace_ids = list(scratch.trace_ids)
        self.frontend = scratch.frontend
        self.model = scratch.model
        self.op = scratch.op
        self.start_ns = scratch.start_ns
        self.end_ns = end_ns
        self.duration_ms = round((end_ns - scratch.start_ns) / 1e6, 6)
        self.error = error
        self.events = scratch.events  # ownership transfers at commit
        self.truncated = scratch.truncated

    def attribution(self) -> Dict[str, Any]:
        """Decompose the timeline's wall time over its event sequence.

        The gap between consecutive events is attributed to the EARLIER
        event's layer (the time that elapsed while that layer's step was
        the latest thing that happened); events carrying a ``url``
        attribute attribute as ``"<layer>:<url>"`` so a slow replica is
        named, not just a slow layer. Returns the per-key milliseconds,
        the dominant key and its share — the per-timeline input to
        :meth:`FlightRecorder.tail_divergence`. Memoized: a timeline is
        immutable after commit, and tail_divergence / doctor snapshots /
        postmortem bundles all re-read the same decomposition."""
        cached = getattr(self, "_attribution", None)
        if cached is not None:
            return cached
        total_ns = max(self.end_ns - self.start_ns, 1)
        keys: Dict[str, float] = {}
        prev_ns = self.start_ns
        prev_key = "pre"
        for ts, layer, _event, attrs in self.events:
            keys[prev_key] = keys.get(prev_key, 0.0) + (ts - prev_ns)
            url = (attrs or {}).get("url")
            prev_key = f"{layer}:{url}" if url else layer
            prev_ns = ts
        keys[prev_key] = keys.get(prev_key, 0.0) + (self.end_ns - prev_ns)
        ms = {k: round(v / 1e6, 4) for k, v in keys.items() if v > 0}
        if not ms:
            out = {"ms": {}, "dominant": None, "dominant_share": 0.0}
        else:
            dominant = max(ms, key=ms.get)
            out = {
                "ms": ms,
                "dominant": dominant,
                "dominant_share": round(keys[dominant] / total_ns, 4),
            }
        self._attribution = out
        return out

    def as_dict(self) -> Dict[str, Any]:
        return {
            "seq": self.seq,
            "verdict": self.verdict,
            "trace_id": self.trace_id,
            "trace_ids": list(self.trace_ids),
            "frontend": self.frontend,
            "model": self.model,
            "op": self.op,
            "start_ns": self.start_ns,
            "end_ns": self.end_ns,
            "duration_ms": self.duration_ms,
            "error": self.error,
            "truncated": self.truncated,
            "events": [
                {"ns": ts, "offset_ms": round((ts - self.start_ns) / 1e6, 4),
                 "layer": layer, "event": event, **(attrs or {})}
                for ts, layer, event, attrs in self.events
            ],
            "attribution": self.attribution(),
        }


class FlightRecorder:
    """Bounded, lock-light ring of per-request causal timelines.

    ``capacity`` bounds the retained ring (oldest evicted);
    ``slow_quantile`` sets the rolling tail threshold behind the ``slow``
    verdict; ``slo_ms`` (optional) declares a hard per-request objective
    behind ``slo_breach``; ``baseline_ratio`` is the healthy-traffic
    reservoir sample; ``max_events`` caps one request's scratch (past it
    events are counted as truncated, never appended — the per-request
    memory bound). ``stream_slow_ttft_quantile`` is the stream twin of
    the slow threshold, fed by per-attempt TTFT.

    Thread-safety: the scratch is context-local (never locked); the
    commit path takes ONE short lock for the verdict bookkeeping and the
    ring append. note()/begin() never block on it."""

    def __init__(
        self,
        capacity: int = 512,
        slow_quantile: float = 0.99,
        baseline_ratio: float = 0.005,
        slo_ms: Optional[float] = None,
        max_events: int = 512,
        threshold_window: int = 2048,
        threshold_min_samples: int = 128,
        rng: Optional[random.Random] = None,
    ):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        if not 0.0 <= baseline_ratio <= 1.0:
            raise ValueError("baseline_ratio must be in [0, 1]")
        if slo_ms is not None and slo_ms <= 0:
            raise ValueError("slo_ms must be > 0")
        self.capacity = int(capacity)
        self.baseline_ratio = float(baseline_ratio)
        self.slo_ms = slo_ms
        self.max_events = max(1, int(max_events))
        self.enabled = True
        self._rng = rng or random.Random()
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._next_seq = itertools.count(1).__next__
        self._threshold = _RollingQuantile(
            slow_quantile, threshold_window,
            min_samples=threshold_min_samples)
        self._stream_threshold = _RollingQuantile(
            slow_quantile, threshold_window,
            min_samples=threshold_min_samples)
        self._counts: Dict[str, int] = {v: 0 for v in FLIGHT_VERDICTS}
        self._dropped = 0
        self._evicted = 0
        self._requests = 0
        self._events_recorded = 0
        self._events_committed = 0
        self._truncated = 0
        # last-N commit costs (ns), split retained vs dropped — the
        # commit-cost halves of BENCH_FLIGHT.json
        self._commit_retained_ns: deque = deque(maxlen=4096)
        self._commit_dropped_ns: deque = deque(maxlen=4096)
        self._telemetry_ref: Optional[Callable[[], Any]] = None
        # commit tap: called with every RETAINED timeline, outside the
        # ring lock (the watchtower's black box drains timelines to disk
        # through this). None = one attribute load + branch per commit.
        self._commit_tap: Optional[Callable[["FlightTimeline"], None]] \
            = None

    # -- lifecycle (the per-request path) ------------------------------------
    def begin(self, frontend: str, model: str = "",
              op: str = "infer") -> Optional[_Scratch]:
        """Open a scratch on this context and become its owner, or None
        when disabled or a request is already being recorded here (the
        caller is a nested layer — it only notes)."""
        if not self.enabled:
            return None
        current = _SCRATCH.get()
        if current is not None and not current.committed:
            return None
        scratch = _Scratch(frontend, model, op, self.max_events)
        scratch.token = _SCRATCH.set(scratch)
        return scratch

    def span_begin(self, span, url: Optional[str] = None) -> None:
        """Called by the endpoint frontends' ``_obs_begin``: bind the new
        wire span's trace id onto the active scratch (opening one owned
        by the span — committed by ``Telemetry.finish`` — when this
        frontend IS the outermost layer)."""
        scratch = _SCRATCH.get()
        if scratch is None or scratch.committed:
            scratch = self.begin(span.frontend, span.model, span.op)
            if scratch is None:
                return
            span.flight = scratch
        if scratch.trace_id is None:
            scratch.trace_id = span.trace_id
        scratch.trace_ids.append(span.trace_id)
        if url:
            scratch.append("span", "begin", trace_id=span.trace_id,
                           frontend=span.frontend, url=url)
        else:
            scratch.append("span", "begin", trace_id=span.trace_id,
                           frontend=span.frontend)

    def _classify_error(self, error: BaseException) -> Tuple[str, str]:
        """(verdict, short error string) for a failed request."""
        from .resilience import SHED, CircuitOpenError, classify_fault

        text = f"{type(error).__name__}: {error}"[:256]
        if isinstance(error, CircuitOpenError):
            return "shed", text
        if classify_fault(error) == SHED:
            return "shed", text
        return "error", text

    def commit(self, scratch: _Scratch,
               error: Optional[BaseException] = None) -> Optional[str]:
        """Settle the request: run the verdict and retain or drop the
        whole timeline. Returns the verdict (None = dropped). Idempotent
        (a double commit is a counted no-op), and always clears the
        contextvar so a leaked scratch can never pollute the next request
        on this thread/task."""
        t0 = time.perf_counter_ns()
        if scratch.committed:
            return None
        scratch.committed = True
        token, scratch.token = scratch.token, None
        if token is not None:
            try:
                _SCRATCH.reset(token)
            except ValueError:
                # committed from a different context than begin (should
                # not happen by construction; never let it leak a scratch)
                _SCRATCH.set(None)
        end_ns = t0
        duration_ms = (end_ns - scratch.start_ns) / 1e6
        verdict: Optional[str] = None
        err_text: Optional[str] = None
        if error is not None:
            verdict, err_text = self._classify_error(error)
        with self._lock:
            self._requests += 1
            self._events_recorded += len(scratch.events)
            if verdict is None:
                if self.slo_ms is not None and duration_ms > self.slo_ms:
                    verdict = "slo_breach"
                else:
                    threshold = self._threshold.threshold()
                    if threshold is not None and duration_ms >= threshold:
                        verdict = "slow"
                    elif (self.baseline_ratio
                          and self._rng.random() < self.baseline_ratio):
                        verdict = "baseline"
                # only successful requests teach the slow threshold
                self._threshold.add(duration_ms)
            if verdict is None:
                self._dropped += 1
                self._commit_dropped_ns.append(
                    time.perf_counter_ns() - t0)
                return None
            timeline = FlightTimeline(
                self._next_seq(), verdict, scratch, end_ns, err_text)
            self._counts[verdict] += 1
            self._events_committed += len(timeline.events)
            self._truncated += timeline.truncated
            if len(self._ring) == self._ring.maxlen:
                self._evicted += 1
            self._ring.append(timeline)
            self._commit_retained_ns.append(time.perf_counter_ns() - t0)
        tap = self._commit_tap
        if tap is not None:
            try:
                tap(timeline)
            except Exception:
                pass  # a sick tap must never fail the request
        return verdict

    def commit_stream(self, span, error: Optional[BaseException] = None,
                      abandoned: bool = False) -> Optional[str]:
        """Settle one finished stream from its :class:`StreamSpan` (the
        streaming paths never hold a scratch open across the generator's
        life — a consumer could interleave unary calls on the same
        thread). The span's attempts and point events (reconnects!)
        synthesize the timeline; verdicts: error/shed as unary,
        ``disrupted`` for a reconnected-but-finished stream, ``slow``
        for a TTFT above the rolling stream threshold, else the baseline
        reservoir."""
        if not self.enabled:
            return None
        t0 = time.perf_counter_ns()
        verdict: Optional[str] = None
        err_text: Optional[str] = None
        if error is not None:
            verdict, err_text = self._classify_error(error)
        ttfts = span.ttft_ms_per_attempt()
        reconnects = len(span.attempts) - 1
        scratch = _Scratch(span.frontend, span.model, span.op,
                           self.max_events)
        scratch.start_ns = span.start_ns
        scratch.trace_id = span.trace_id
        scratch.trace_ids = [span.trace_id]
        for i, attempt in enumerate(span.attempts):
            scratch.events.append(
                (attempt.start_ns, "stream", "attempt",
                 {"attempt": i, "chunks": len(attempt.marks)}))
        for name, ts, attrs in (getattr(span, "events", None) or ()):
            scratch.events.append((ts, "stream", name, attrs))
        scratch.events.sort(key=lambda e: e[0])
        end_ns = getattr(span, "end_ns", 0) or t0
        duration_ms = (end_ns - span.start_ns) / 1e6
        with self._lock:
            self._requests += 1
            self._events_recorded += len(scratch.events)
            if verdict is None:
                if abandoned:
                    verdict = "error"
                    err_text = "abandoned by consumer"
                elif self.slo_ms is not None and duration_ms > self.slo_ms:
                    # the declared objective applies to streams too: a
                    # grossly-over-budget session is retained even when
                    # its TTFT was fast and nothing reconnected
                    verdict = "slo_breach"
                elif reconnects:
                    verdict = "disrupted"
                else:
                    threshold = self._stream_threshold.threshold()
                    if (ttfts and threshold is not None
                            and ttfts[0] >= threshold):
                        verdict = "slow"
                    elif (self.baseline_ratio
                          and self._rng.random() < self.baseline_ratio):
                        verdict = "baseline"
                if ttfts:
                    self._stream_threshold.add(ttfts[0])
            if verdict is None:
                self._dropped += 1
                self._commit_dropped_ns.append(
                    time.perf_counter_ns() - t0)
                return None
            timeline = FlightTimeline(
                self._next_seq(), verdict, scratch, end_ns, err_text)
            self._counts[verdict] += 1
            self._events_committed += len(timeline.events)
            if len(self._ring) == self._ring.maxlen:
                self._evicted += 1
            self._ring.append(timeline)
            self._commit_retained_ns.append(time.perf_counter_ns() - t0)
        tap = self._commit_tap
        if tap is not None:
            try:
                tap(timeline)
            except Exception:
                pass
        return verdict

    def set_commit_tap(
            self, tap: Optional[Callable[["FlightTimeline"], None]]) -> None:
        """Install (or clear, with None) the retained-timeline tap: called
        with every timeline the verdict keeps, after the ring append and
        outside the ring lock. With no tap the commit path pays one
        attribute load + branch (the BENCH_WATCH.json disabled-path
        claim). Exceptions from the tap are swallowed."""
        self._commit_tap = tap

    def mark(self, layer: str, event: str, **attrs) -> Optional[str]:
        """Retain one out-of-band single-event marker timeline (verdict
        ``mark``) with no request context — the watchtower records its
        ``watch.alert`` firing/resolved edges here so every alert is
        attributable next to the request timelines around it. Marks show
        in :meth:`last_anomalies` (they ARE worth explaining) but never
        count as tail evidence for :meth:`tail_divergence`."""
        if not self.enabled:
            return None
        now = time.perf_counter_ns()
        scratch = _Scratch("watch", "", event, self.max_events)
        scratch.start_ns = now
        scratch.events.append((now, layer, event, attrs or None))
        scratch.committed = True
        with self._lock:
            timeline = FlightTimeline(
                self._next_seq(), "mark", scratch, now, None)
            self._counts["mark"] += 1
            self._events_committed += 1
            if len(self._ring) == self._ring.maxlen:
                self._evicted += 1
            self._ring.append(timeline)
        return "mark"

    # -- read side -----------------------------------------------------------
    def retained(self, count: Optional[int] = None) -> List[FlightTimeline]:
        """The retained timelines, oldest first (a bounded snapshot)."""
        with self._lock:
            timelines = list(self._ring)
        if count is not None:
            timelines = timelines[-count:]
        return timelines

    def last_anomalies(self, count: int = 16) -> List[Dict[str, Any]]:
        """The newest ``count`` NON-baseline retained timelines (error/
        shed/slo_breach/slow/disrupted), newest first, as dicts — the
        "why were my last requests slow" accessor."""
        with self._lock:
            timelines = [t for t in self._ring if t.verdict != "baseline"]
        return [t.as_dict() for t in reversed(timelines[-count:])]

    def find(self, trace_id: str) -> Optional[FlightTimeline]:
        """The retained timeline containing ``trace_id`` (any wire span of
        the request — exemplar trace ids resolve here), if still in the
        ring."""
        with self._lock:
            for timeline in reversed(self._ring):
                if (timeline.trace_id == trace_id
                        or trace_id in timeline.trace_ids):
                    return timeline
        return None

    def bind(self, telemetry) -> None:
        """Attach to a Telemetry: export retained/dropped gauges on its
        registry at scrape time, and let :meth:`to_chrome_trace` merge
        with its tracer ring. Called by ``Telemetry(flight=...)``."""
        self._telemetry_ref = weakref.ref(telemetry)
        reg = telemetry.registry
        retained_g = reg.gauge(
            "client_tpu_flight_retained_total",
            "Flight timelines retained by the tail-based verdict",
            ("verdict",))
        dropped_g = reg.gauge(
            "client_tpu_flight_dropped_total",
            "Requests whose flight timeline was dropped wholesale "
            "(fast + healthy)")
        ring_g = reg.gauge(
            "client_tpu_flight_ring",
            "Retained timelines currently in the bounded ring")

        def collect() -> None:
            stats = self.stats()
            for verdict, n in stats["retained"].items():
                retained_g.labels(verdict).set(n)
            dropped_g.set(stats["dropped"])
            ring_g.set(stats["ring"])

        reg.add_collector(collect)

    def stats(self) -> Dict[str, Any]:
        """JSON-ready accounting incl. the commit-cost percentiles the
        perf harness emits as ``client_flight``."""
        from .utils import sorted_percentile

        with self._lock:
            retained_ns = sorted(self._commit_retained_ns)
            dropped_ns = sorted(self._commit_dropped_ns)
            counts = dict(self._counts)
            out: Dict[str, Any] = {
                "requests": self._requests,
                "retained": counts,
                "retained_total": sum(counts.values()),
                "dropped": self._dropped,
                "evicted": self._evicted,
                "ring": len(self._ring),
                "capacity": self.capacity,
                "events_recorded": self._events_recorded,
                "events_committed": self._events_committed,
                "truncated_events": self._truncated,
                "slow_threshold_ms": self._threshold.threshold(),
            }
        out["retained_fraction"] = (
            round(out["retained_total"] / out["requests"], 6)
            if out["requests"] else 0.0)
        out["events_per_request"] = (
            round(out["events_recorded"] / out["requests"], 3)
            if out["requests"] else 0.0)
        for label, samples in (("commit_retained_ns", retained_ns),
                               ("commit_dropped_ns", dropped_ns)):
            if samples:
                out[label] = {
                    "p50": round(sorted_percentile(samples, 0.5), 1),
                    "p99": round(sorted_percentile(samples, 0.99), 1),
                }
        return out

    # -- anomaly detection ----------------------------------------------------
    def tail_divergence(self, min_tail: int = 8,
                        min_share: float = 0.6) -> Optional[Dict[str, Any]]:
        """Do the retained TAIL timelines (slow/slo_breach) share one
        dominant attribution key that the baseline/median traffic does
        not? That shape — "every slow request spent its time in the same
        layer (or behind the same endpoint), the typical request did
        not" — is the classic one-bad-replica / one-hot-lock signature.

        Returns None when there is no divergence (or not enough tail
        evidence); else a dict naming the dominant key, its tail share
        and the baseline share — the doctor surfaces it as the
        ``tail_divergence`` anomaly."""
        with self._lock:
            timelines = list(self._ring)
        tail = [t for t in timelines
                if t.verdict in ("slow", "slo_breach")]
        if len(tail) < min_tail:
            return None
        base = [t for t in timelines if t.verdict == "baseline"]

        def dominants(group: List[FlightTimeline]) -> Dict[str, int]:
            counts: Dict[str, int] = {}
            for t in group:
                key = t.attribution()["dominant"]
                if key is not None:
                    counts[key] = counts.get(key, 0) + 1
            return counts

        tail_counts = dominants(tail)
        if not tail_counts:
            return None
        key = max(tail_counts, key=tail_counts.get)
        tail_share = tail_counts[key] / len(tail)
        if tail_share < min_share:
            return None
        base_counts = dominants(base)
        base_share = (base_counts.get(key, 0) / len(base)) if base else 0.0
        # the tail concentrating where the median does NOT is the signal;
        # when baseline traffic concentrates in the same place the slow
        # tail is just "everything is slow", not a divergence
        if base and base_share >= tail_share / 2.0:
            return None
        return {
            "dominant": key,
            "tail_count": len(tail),
            "tail_share": round(tail_share, 4),
            "baseline_count": len(base),
            "baseline_share": round(base_share, 4),
        }

    # -- exporters -------------------------------------------------------------
    def to_chrome_trace(self, tracer=None) -> Dict[str, Any]:
        """Chrome ``trace_event`` JSON over the retained timelines: one
        complete ("X") event per retained request, instant ("i") events
        per flight event, MERGED with the phase intervals of every
        :class:`~client_tpu.observe.RequestSpan` in the tracer ring whose
        trace id belongs to a retained timeline (``tracer`` defaults to
        the bound Telemetry's). Events are emitted sorted by timestamp —
        the same contract as ``Tracer.chrome_trace``."""
        if tracer is None and self._telemetry_ref is not None:
            telemetry = self._telemetry_ref()
            if telemetry is not None:
                tracer = telemetry.tracer
        timelines = self.retained()
        events: List[Dict[str, Any]] = []
        by_trace: Dict[str, int] = {}
        for timeline in timelines:
            tid = timeline.seq
            for trace_id in timeline.trace_ids:
                by_trace[trace_id] = tid
            name = f"{timeline.op} {timeline.model}".strip()
            events.append({
                "name": f"{name} [{timeline.verdict}]",
                "cat": timeline.frontend or "flight", "ph": "X",
                "ts": timeline.start_ns / 1e3,
                "dur": max(timeline.end_ns - timeline.start_ns, 0) / 1e3,
                "pid": 1, "tid": tid,
                "args": {"trace_id": timeline.trace_id,
                         "verdict": timeline.verdict,
                         "error": timeline.error},
            })
            for ts, layer, event, attrs in timeline.events:
                events.append({
                    "name": f"{layer}.{event}", "cat": layer, "ph": "i",
                    "ts": ts / 1e3, "s": "t", "pid": 1, "tid": tid,
                    "args": attrs or {},
                })
        if tracer is not None:
            with tracer._lock:
                spans = list(tracer._ring)
            for span in spans:
                tid = by_trace.get(span.trace_id)
                if tid is None:
                    continue
                for pname, s, e in span.phases:
                    events.append({
                        "name": pname, "cat": "phase", "ph": "X",
                        "ts": s / 1e3, "dur": max(e - s, 0) / 1e3,
                        "pid": 1, "tid": tid,
                        "args": {"trace_id": span.trace_id},
                    })
        events.sort(key=lambda e: e["ts"])
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def dump_jsonl(self, path: Optional[str] = None) -> Any:
        """The retained timelines as JSON-lines (one timeline per line,
        oldest first). Returns the string, or the timeline count when
        ``path`` is given (written atomically enough for a postmortem:
        one open/write/close)."""
        lines = [json.dumps(t.as_dict(), separators=(",", ":"))
                 for t in self.retained()]
        text = "\n".join(lines) + ("\n" if lines else "")
        if path is None:
            return text
        with open(path, "w") as f:
            f.write(text)
        return len(lines)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
