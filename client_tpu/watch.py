"""Continuous monitoring: crash-safe black box, multi-window burn-rate
alerting, and a seeded deterministic changepoint watchdog.

Everything before this module answers questions at a *point in time* —
spans, OpenMetrics scrapes, the flight recorder, ``doctor``. This module
is the continuous layer over the same telemetry, in three pillars:

- **Crash-safe black box** (:class:`BlackBox` / :func:`read_blackbox`):
  an mmap-backed on-disk ring of length-prefixed, checksummed records.
  The flight recorder drains every retained timeline into it at commit
  (``FlightRecorder.set_commit_tap``), the metrics registry drains its
  snapshot at scrape (``MetricsRegistry.add_drain``), and every alert
  edge lands as its own record — so ``python -m client_tpu.doctor
  --blackbox PATH`` reconstructs the last N retained timelines, the last
  metric snapshot and the last alerts after a ``kill -9``, from the ring
  file alone. Torn tails and bit flips are *skipped, never raised*: the
  reader validates each record's magic, length bound and CRC32 and
  returns only the records that verify.

- **Multi-window burn-rate alerting**: every declared ``observe.SLO``
  gets a fast/slow dual-window burn evaluation over its OWN windowed
  sketch (``SLO.burn_rate(window_s)`` reads the newest sub-windows; the
  plain call reads the full window) — an alert fires only when BOTH
  windows burn past their thresholds, the Google-SRE shape that pages on
  sustained burn without flapping on blips. Watermark rules cover the
  non-SLO pressure gauges: pool breakers open, byzantine quarantines,
  admission shed rate, arena residency and federation cells down.
  Alerts are typed :class:`Alert` objects with firing/resolved edge
  semantics, per-(kind, source) deduplication, pluggable sinks
  (callback, :class:`JsonlSink`, the black box) and a ``watch.alert``
  flight mark so every alert is attributable in the retained ring.

- **Changepoint watchdog**: one-sided standardized CUSUM detectors
  (:class:`Cusum`; :class:`PageHinkley` for raw-valued streams) over the
  ``WindowedSketch`` streams — request p99, TTFT p99, ITL p99, shed
  rate — deterministic given the sample stream (no wall-clock
  randomness; the ``seed`` only names the run). On trip the watchdog
  runs ``flight.tail_divergence()`` and the retained timelines'
  attribution to name the layer/endpoint that moved, distinguishing
  "one replica went bad" (a dominant key) from "the fleet shifted"
  (``fleet_shift``). After a trip the detector re-enters warmup, so a
  persistent new level is re-learned instead of re-alerted.

Wiring: ``Watchtower(telemetry, blackbox="/path/ring.bbx").start()``
arms everything (or :func:`enable_watchtower` for the process-global
instance, same install pattern as ``observe.enable_dataplane``). With
no watchtower installed the hot paths pay exactly one branch each
(flight commit tap None, registry drains empty) — the disabled-path
claim proven in BENCH_WATCH.json next to the enabled tick cost,
time-to-detect under live injected chaos, and a zero-false-positive
A/A soak. See docs/observability.md "Continuous monitoring & black
box".
"""

from __future__ import annotations

import json
import mmap
import os
import struct
import threading
import time
import zlib
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "Alert",
    "BlackBox",
    "BlackBoxRecord",
    "BlackBoxReport",
    "Cusum",
    "JsonlSink",
    "PageHinkley",
    "WatermarkRule",
    "Watchtower",
    "blackbox_report",
    "enable_watchtower",
    "install_watchtower",
    "read_blackbox",
    "watchtower",
]


# -- crash-safe black box -----------------------------------------------------
# On-disk layout: a 64-byte file header, then a fixed-capacity data ring.
#   header: <8s I I Q  = magic "CTPUBBX1", version, reserved, capacity
#   record: <I I I I Q d = magic, payload_len, crc32, reserved, seq, unix_ts
#           followed by the JSON payload, zero-padded to 8 bytes.
# Records are written payload-first, header-last, at 8-aligned offsets;
# the CRC covers (seq, ts, payload). A reader therefore never needs the
# writer's head pointer: it scans every aligned offset, keeps exactly the
# records whose magic + length bound + CRC verify, and orders them by
# seq. A torn tail (kill -9 mid-write), a truncated file or a flipped
# bit invalidates only the records it touched — skipped, never raised.
_FILE_MAGIC = b"CTPUBBX1"
_FILE_HEADER = struct.Struct("<8sIIQ")
_FILE_HEADER_SIZE = 64
_FILE_VERSION = 1
_REC_MAGIC = 0x42425752  # "RWBB" little-endian
_REC_HEADER = struct.Struct("<IIIIQd")
_REC_HEADER_SIZE = _REC_HEADER.size  # 32
_ALIGN = 8


def _pad8(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


class BlackBoxRecord:
    """One verified black-box record: ``kind`` is the record type
    (``meta`` / ``timeline`` / ``metrics`` / ``alert``), ``data`` the
    JSON payload, ``seq`` the writer's monotonic sequence number and
    ``ts`` the wall-clock write time."""

    __slots__ = ("seq", "ts", "kind", "data")

    def __init__(self, seq: int, ts: float, kind: str, data: Any):
        self.seq = seq
        self.ts = ts
        self.kind = kind
        self.data = data

    def as_dict(self) -> Dict[str, Any]:
        return {"seq": self.seq, "ts": self.ts, "kind": self.kind,
                "data": self.data}


@dataclass
class BlackBoxReport:
    """The outcome of scanning a ring file: only verified records, plus
    honest accounting of what was skipped. Never raises on corruption —
    ``ok`` is False only when the file itself is absent/unreadable or
    carries no valid header."""

    ok: bool
    note: str
    records: List[BlackBoxRecord] = field(default_factory=list)
    stats: Dict[str, Any] = field(default_factory=dict)

    def by_kind(self, kind: str) -> List[BlackBoxRecord]:
        return [r for r in self.records if r.kind == kind]

    def last(self, kind: str) -> Optional[BlackBoxRecord]:
        rows = self.by_kind(kind)
        return rows[-1] if rows else None


def _scan_region(data: bytes) -> Tuple[List[Tuple[int, int, float, bytes]],
                                       Dict[str, int]]:
    """Scan one data region for verified records. Returns
    ``[(seq, end_offset, ts, payload)]`` (unordered) and scan stats.
    Pure bytes in, never raises: every candidate must pass the magic,
    the length bound AND the CRC before its payload is even parsed."""
    found: List[Tuple[int, int, float, bytes]] = []
    stats = {"scanned": 0, "valid": 0, "rejected": 0}
    size = len(data)
    off = 0
    while off + _REC_HEADER_SIZE <= size:
        stats["scanned"] += 1
        magic, length, crc, _reserved, seq, ts = _REC_HEADER.unpack_from(
            data, off)
        if magic != _REC_MAGIC or length == 0 \
                or off + _REC_HEADER_SIZE + length > size:
            off += _ALIGN
            continue
        payload = bytes(data[off + _REC_HEADER_SIZE:
                             off + _REC_HEADER_SIZE + length])
        if zlib.crc32(struct.pack("<Qd", seq, ts) + payload) != crc:
            stats["rejected"] += 1
            off += _ALIGN
            continue
        end = off + _REC_HEADER_SIZE + _pad8(length)
        found.append((seq, end, ts, payload))
        stats["valid"] += 1
        off = end
    return found, stats


class BlackBox:
    """The mmap-backed crash-safe ring writer.

    ``capacity_bytes`` bounds the data region; records wrap (oldest
    overwritten by position). Writes are payload-first/header-last under
    one lock, so a ``kill -9`` tears at most the record in flight — and
    a torn record fails its CRC and is skipped by every reader. mmap
    pages survive process death without ``flush()`` (the page cache owns
    them); ``flush()`` exists for machine-crash durability.

    Reopening an existing ring recovers: the constructor scans for the
    highest verified seq and continues after it."""

    def __init__(self, path: str, capacity_bytes: int = 1 << 22):
        capacity = _pad8(max(int(capacity_bytes), 4096))
        self.path = str(path)
        self._lock = threading.Lock()
        self._appended = 0
        self._dropped_oversize = 0
        self._wrapped = 0
        size = _FILE_HEADER_SIZE + capacity
        fresh = True
        if os.path.exists(self.path) \
                and os.path.getsize(self.path) >= _FILE_HEADER_SIZE:
            with open(self.path, "rb") as f:
                head = f.read(_FILE_HEADER.size)
            try:
                magic, version, _, existing_cap = _FILE_HEADER.unpack(head)
                # a valid header is enough: a truncated file (crashed
                # mid-grow, copied short) is re-grown zero-filled below
                # and its surviving records recovered
                fresh = not (magic == _FILE_MAGIC
                             and version == _FILE_VERSION
                             and existing_cap > 0)
                if not fresh:
                    capacity = int(existing_cap)
                    size = _FILE_HEADER_SIZE + capacity
            except struct.error:
                fresh = True
        self.capacity = capacity
        flags = os.O_RDWR | os.O_CREAT
        fd = os.open(self.path, flags, 0o644)
        try:
            if fresh:
                os.ftruncate(fd, 0)
            if os.fstat(fd).st_size != size:
                os.ftruncate(fd, size)  # grow is zero-filled
            self._mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        if fresh:
            self._mm[:_FILE_HEADER.size] = _FILE_HEADER.pack(
                _FILE_MAGIC, _FILE_VERSION, 0, capacity)
            self._head = 0
            self._seq = 1
        else:
            found, _ = _scan_region(
                self._mm[_FILE_HEADER_SIZE:_FILE_HEADER_SIZE + capacity])
            if found:
                newest = max(found, key=lambda rec: rec[0])
                self._seq = newest[0] + 1
                self._head = newest[1] % capacity
            else:
                self._head = 0
                self._seq = 1
        self._closed = False

    def append(self, kind: str, data: Any) -> bool:
        """Write one record (JSON-serialized ``{"kind", "data"}``).
        Returns False (counted) when the payload cannot fit the ring."""
        payload = json.dumps({"kind": kind, "data": data},
                             separators=(",", ":"), default=str).encode()
        total = _REC_HEADER_SIZE + _pad8(len(payload))
        with self._lock:
            if self._closed:
                return False
            if total > self.capacity:
                self._dropped_oversize += 1
                return False
            if self._head + total > self.capacity:
                self._wrapped += 1
                self._head = 0
            base = _FILE_HEADER_SIZE + self._head
            seq = self._seq
            ts = time.time()
            crc = zlib.crc32(struct.pack("<Qd", seq, ts) + payload)
            # payload first, header (with its magic+CRC) last: a kill -9
            # between the two leaves a record that fails verification
            # instead of a record that parses as garbage
            self._mm[base + _REC_HEADER_SIZE:
                     base + _REC_HEADER_SIZE + len(payload)] = payload
            self._mm[base:base + _REC_HEADER_SIZE] = _REC_HEADER.pack(
                _REC_MAGIC, len(payload), crc, 0, seq, ts)
            self._head += total
            self._seq += 1
            self._appended += 1
        return True

    def flush(self) -> None:
        with self._lock:
            if not self._closed:
                self._mm.flush()

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            try:
                self._mm.flush()
            finally:
                self._mm.close()

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "path": self.path,
                "capacity_bytes": self.capacity,
                "appended": self._appended,
                "dropped_oversize": self._dropped_oversize,
                "wrapped": self._wrapped,
                "next_seq": self._seq,
            }


def read_blackbox(path: str) -> BlackBoxReport:
    """Scan a black-box ring file and return every record that verifies,
    ordered by seq. NEVER raises on corruption: truncation, torn tails,
    bit flips and partial overwrites invalidate only the records they
    touch (magic/length-bound/CRC check), and a missing or headerless
    file returns an empty not-ok report."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError as exc:
        return BlackBoxReport(ok=False, note=f"unreadable: {exc}")
    if len(raw) < _FILE_HEADER.size:
        return BlackBoxReport(ok=False, note="no valid header (truncated)")
    magic, version, _, capacity = _FILE_HEADER.unpack_from(raw, 0)
    if magic != _FILE_MAGIC:
        return BlackBoxReport(ok=False, note="no valid header (bad magic)")
    # clamp to what is actually on disk: a truncated ring still yields
    # every record that fully survived
    region = raw[_FILE_HEADER_SIZE:_FILE_HEADER_SIZE + capacity]
    found, stats = _scan_region(region)
    records: List[BlackBoxRecord] = []
    seen: set = set()
    for seq, _end, ts, payload in sorted(found, key=lambda rec: rec[0]):
        if seq in seen:
            continue
        try:
            doc = json.loads(payload)
        except ValueError:
            stats["rejected"] += 1
            continue
        if not isinstance(doc, dict) or not isinstance(doc.get("kind"), str):
            stats["rejected"] += 1
            continue
        seen.add(seq)
        records.append(BlackBoxRecord(seq, ts, doc["kind"], doc.get("data")))
    stats["version"] = version
    stats["capacity_bytes"] = capacity
    return BlackBoxReport(ok=True, note="", records=records, stats=stats)


def blackbox_report(path: str, timelines: int = 16) -> Dict[str, Any]:
    """The ``doctor --blackbox`` reconstruction: one JSON-pure dict with
    the last retained timelines, the last metrics snapshot, every
    recovered alert and the run metadata — rebuilt from the ring file
    alone (no live process)."""
    report = read_blackbox(path)
    out: Dict[str, Any] = {
        "kind": "client_tpu_blackbox",
        "path": str(path),
        "ok": report.ok,
        "note": report.note,
        "scan": report.stats,
        "records": len(report.records),
    }
    if not report.ok:
        return out
    meta = report.last("meta")
    out["meta"] = meta.data if meta else None
    tl_records = report.by_kind("timeline")
    out["timelines_recovered"] = len(tl_records)
    out["timelines"] = [r.data for r in tl_records[-timelines:]]
    metrics = report.last("metrics")
    out["metrics"] = metrics.data if metrics else None
    out["metrics_snapshots_recovered"] = len(report.by_kind("metrics"))
    alerts = [dict(r.data, recorded_unix=r.ts)
              for r in report.by_kind("alert")
              if isinstance(r.data, dict)]
    out["alerts"] = alerts
    out["last_alert"] = alerts[-1] if alerts else None
    return out


# -- alerts -------------------------------------------------------------------
@dataclass
class Alert:
    """One typed alert. ``kind`` is the rule family (``slo_burn`` /
    ``watermark`` / ``changepoint``), ``source`` the deduplication key
    within it (e.g. ``slo:ttft_p95`` or ``gauge:pool.quarantined``),
    ``evidence`` the numbers behind the verdict (burn rates, gauge
    values, the flight divergence that names the moved endpoint)."""

    kind: str
    severity: str
    source: str
    evidence: Dict[str, Any]
    state: str = "firing"
    fired_unix: float = 0.0
    resolved_unix: Optional[float] = None

    def as_dict(self) -> Dict[str, Any]:
        return {
            "kind": self.kind,
            "severity": self.severity,
            "source": self.source,
            "state": self.state,
            "fired_unix": self.fired_unix,
            "resolved_unix": self.resolved_unix,
            "evidence": self.evidence,
        }


class JsonlSink:
    """An alert sink appending one JSON line per firing/resolved edge."""

    def __init__(self, path: str):
        self.path = str(path)
        self._lock = threading.Lock()

    def __call__(self, alert: Alert) -> None:
        line = json.dumps(alert.as_dict(), separators=(",", ":"),
                          default=str)
        with self._lock:
            with open(self.path, "a") as f:
                f.write(line + "\n")


@dataclass
class WatermarkRule:
    """Fire when a collected gauge crosses ``threshold``; resolve when it
    falls back below ``clear`` (defaults to the threshold — integer
    occupancy gauges like breakers-open want exact edges; rate gauges
    pass a lower ``clear`` for hysteresis)."""

    name: str
    key: str
    threshold: float
    clear: Optional[float] = None
    severity: str = "ticket"

    def clear_level(self) -> float:
        return self.threshold if self.clear is None else self.clear


# -- changepoint detectors ----------------------------------------------------
class PageHinkley:
    """Classic Page-Hinkley test for an upward mean shift on raw values:
    maintains the running mean and the cumulative deviation
    ``m_t = Σ (x_i - mean_i - delta)``; trips when ``m_t`` rises more
    than ``threshold`` above its running minimum. Fully deterministic
    given the sample stream. ``reset()`` (automatic after a trip)
    restarts the test so a persistent shift is learned, not re-alerted."""

    __slots__ = ("delta", "threshold", "min_samples", "n", "mean",
                 "_m", "_m_min", "trips")

    def __init__(self, delta: float = 0.05, threshold: float = 50.0,
                 min_samples: int = 16):
        self.delta = float(delta)
        self.threshold = float(threshold)
        self.min_samples = max(1, int(min_samples))
        self.trips = 0
        self.reset()

    def reset(self) -> None:
        self.n = 0
        self.mean = 0.0
        self._m = 0.0
        self._m_min = 0.0

    def update(self, x: float) -> bool:
        x = float(x)
        self.n += 1
        self.mean += (x - self.mean) / self.n
        self._m += x - self.mean - self.delta
        self._m_min = min(self._m_min, self._m)
        if (self.n >= self.min_samples
                and self._m - self._m_min > self.threshold):
            self.trips += 1
            self.reset()
            return True
        return False

    def state(self) -> Dict[str, Any]:
        return {"detector": "page_hinkley", "n": self.n,
                "mean": round(self.mean, 4),
                "m": round(self._m - self._m_min, 4),
                "threshold": self.threshold, "trips": self.trips}


class Cusum:
    """One-sided (upward) standardized CUSUM with a Welford warmup.

    The first ``warmup`` samples learn the stream's mean/σ and never
    trip; after that each sample is standardized and accumulated as
    ``g = max(0, g + z - k)``, tripping when ``g > h`` — the classic
    sequential test for a sustained upward shift. σ is floored at
    ``rel_floor·|mean|`` and ``abs_floor`` so a bucket-quantized
    (near-constant) stream cannot manufacture infinite z-scores, and
    the baseline drifts only on unsuspicious samples (``z < k``) so a
    real shift cannot teach itself away before tripping. Deterministic
    given the sample stream; after a trip the detector re-enters warmup
    and adapts to the new level."""

    __slots__ = ("k", "h", "warmup", "rel_floor", "abs_floor", "drift",
                 "n", "mean", "_m2", "g", "trips")

    def __init__(self, k: float = 0.5, h: float = 8.0, warmup: int = 24,
                 rel_floor: float = 0.1, abs_floor: float = 0.5,
                 drift: float = 0.02):
        self.k = float(k)
        self.h = float(h)
        self.warmup = max(2, int(warmup))
        self.rel_floor = float(rel_floor)
        self.abs_floor = float(abs_floor)
        self.drift = float(drift)
        self.trips = 0
        self.reset()

    def reset(self) -> None:
        self.n = 0
        self.mean = 0.0
        self._m2 = 0.0
        self.g = 0.0

    def sigma(self) -> float:
        var = self._m2 / max(self.n - 1, 1)
        return max(var ** 0.5, self.rel_floor * abs(self.mean),
                   self.abs_floor)

    def update(self, x: float) -> bool:
        x = float(x)
        if self.n < self.warmup:
            self.n += 1
            delta = x - self.mean
            self.mean += delta / self.n
            self._m2 += delta * (x - self.mean)
            return False
        z = (x - self.mean) / self.sigma()
        self.g = max(0.0, self.g + z - self.k)
        if self.g > self.h:
            self.trips += 1
            self.reset()
            return True
        if z < self.k:
            self.mean += self.drift * (x - self.mean)
        return False

    def state(self) -> Dict[str, Any]:
        return {"detector": "cusum", "n": self.n,
                "armed": self.n >= self.warmup,
                "mean": round(self.mean, 4),
                "sigma": round(self.sigma(), 4) if self.n > 1 else None,
                "g": round(self.g, 4), "h": self.h, "trips": self.trips}


# -- the watchtower -----------------------------------------------------------
class Watchtower:
    """The background monitor over one ``observe.Telemetry``.

    Each tick (``interval_s``; :meth:`tick` is also public and
    synchronous for tests/benches) it:

    1. folds pending spans so the windowed sketches are fresh;
    2. evaluates fast/slow dual-window burn for every declared SLO
       (fires only when BOTH windows exceed their thresholds);
    3. collects watermark gauges from the telemetry's registered pools
       (breakers open, quarantined replicas), admission controllers
       (shed rate over the tick interval), federations (cells down) and
       live arenas (residency fraction), and evaluates the watermark
       rules with firing/resolved hysteresis;
    4. samples the ``WindowedSketch`` streams (request/TTFT/ITL p99 over
       the fast window, plus shed rate) into per-stream CUSUM detectors;
       a trip consults ``flight.tail_divergence()`` to name the moved
       endpoint/layer — or calls it a ``fleet_shift``;
    5. emits alert EDGES (fire once, resolve once — deduplicated on
       ``(kind, source)`` while active) to every sink, the black box,
       and the flight ring (``watch.alert`` marks).

    With ``blackbox`` armed it also installs the flight commit tap and
    the registry scrape drain, and writes a rate-limited metrics record
    per ``metrics_every_ticks`` ticks — the crash-surviving record
    ``doctor --blackbox`` reconstructs."""

    _STREAM_METRICS = ("request_ms", "ttft_ms", "itl_ms")

    def __init__(
        self,
        telemetry,
        interval_s: float = 1.0,
        blackbox: Optional[Any] = None,
        sinks: Tuple[Callable[[Alert], None], ...] = (),
        fast_window_s: float = 60.0,
        fast_burn_threshold: float = 6.0,
        slow_burn_threshold: float = 1.0,
        shed_rate_watermark: float = 0.5,
        arena_watermark: float = 0.9,
        changepoint: bool = True,
        cusum_k: float = 0.5,
        cusum_h: float = 8.0,
        cusum_warmup: int = 24,
        min_stream_count: int = 8,
        metrics_every_ticks: int = 10,
        history: int = 256,
        seed: int = 0,
        flight_marks: bool = True,
    ):
        if interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        self.telemetry = telemetry
        self.interval_s = float(interval_s)
        self.fast_window_s = float(fast_window_s)
        self.fast_burn_threshold = float(fast_burn_threshold)
        self.slow_burn_threshold = float(slow_burn_threshold)
        self.changepoint = bool(changepoint)
        self.cusum_k = float(cusum_k)
        self.cusum_h = float(cusum_h)
        self.cusum_warmup = int(cusum_warmup)
        self.min_stream_count = max(1, int(min_stream_count))
        self.metrics_every_ticks = max(1, int(metrics_every_ticks))
        self.seed = int(seed)
        self.flight_marks = bool(flight_marks)
        self.sinks: List[Callable[[Alert], None]] = list(sinks)
        self._owns_blackbox = isinstance(blackbox, (str, os.PathLike))
        self.blackbox: Optional[BlackBox] = (
            BlackBox(blackbox) if self._owns_blackbox else blackbox)
        self.watermarks: List[WatermarkRule] = [
            WatermarkRule("breakers_open", "pool.breakers_open", 1.0),
            WatermarkRule("quarantined_replicas", "pool.quarantined", 1.0),
            WatermarkRule("shed_rate", "admission.shed_rate",
                          float(shed_rate_watermark),
                          clear=float(shed_rate_watermark) / 2.0),
            WatermarkRule("arena_residency", "arena.leased_fraction",
                          float(arena_watermark),
                          clear=float(arena_watermark) * 0.8),
            WatermarkRule("cells_down", "federation.cells_down", 1.0,
                          severity="page"),
        ]
        self._lock = threading.Lock()
        self._active: Dict[Tuple[str, str], Alert] = {}
        self._history: deque = deque(maxlen=max(8, int(history)))
        self._fired: Dict[str, int] = {}
        self._resolved: Dict[str, int] = {}
        self._detectors: Dict[str, Cusum] = {}
        self._changepoint_trips = 0
        self._prev_admission: Optional[Tuple[float, float]] = None
        self._ticks = 0
        self._tick_errors = 0
        self._tick_ns: deque = deque(maxlen=4096)
        self._metrics_tick = 0
        self._last_metrics_drain = 0.0
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._stopped = False
        if self.blackbox is not None:
            self.blackbox.append("meta", {
                "pid": os.getpid(),
                "started_unix": round(time.time(), 3),
                "interval_s": self.interval_s,
                "seed": self.seed,
                "version": 1,
            })
            registry = getattr(telemetry, "registry", None)
            if registry is not None and hasattr(registry, "add_drain"):
                registry.add_drain(self._drain_metrics)
            recorder = getattr(telemetry, "flight", None)
            if recorder is not None and hasattr(recorder, "set_commit_tap"):
                recorder.set_commit_tap(self._drain_timeline)

    # -- black-box drains ----------------------------------------------------
    def _drain_metrics(self, snapshot: Dict[str, Any]) -> None:
        """Registry scrape-drain hook: persist the snapshot, rate-limited
        so a hot scrape loop cannot churn the whole ring."""
        bb = self.blackbox
        if bb is None or self._stopped:
            return
        now = time.monotonic()
        if now - self._last_metrics_drain < min(self.interval_s, 1.0):
            return
        self._last_metrics_drain = now
        bb.append("metrics", snapshot)

    def _drain_timeline(self, timeline) -> None:
        """Flight commit tap: every retained timeline lands in the ring
        (tail-based retention already bounds the volume)."""
        bb = self.blackbox
        if bb is None or self._stopped:
            return
        try:
            bb.append("timeline", timeline.as_dict())
        except Exception:
            pass

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "Watchtower":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="client-tpu-watchtower", daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop_event.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                self._tick_errors += 1

    def stop(self) -> None:
        self._stop_event.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=max(2.0, 4 * self.interval_s))
        self._stopped = True
        recorder = getattr(self.telemetry, "flight", None)
        if recorder is not None and hasattr(recorder, "set_commit_tap"):
            recorder.set_commit_tap(None)
        registry = getattr(self.telemetry, "registry", None)
        if registry is not None and hasattr(registry, "remove_drain"):
            registry.remove_drain(self._drain_metrics)
        if self.blackbox is not None:
            try:
                self.blackbox.append("meta", {
                    "pid": os.getpid(),
                    "stopped_unix": round(time.time(), 3),
                })
                self.blackbox.flush()
            finally:
                if self._owns_blackbox:
                    self.blackbox.close()

    # -- one evaluation ------------------------------------------------------
    def tick(self) -> List[Alert]:
        """One synchronous evaluation pass; returns the alert EDGES it
        emitted (fired or resolved this tick)."""
        t0 = time.perf_counter_ns()
        tel = self.telemetry
        try:
            tel._fold_pending()
            tel._fold_stream_pending()
        except Exception:
            pass
        edges: List[Alert] = []
        edges += self._eval_burn()
        gauges, details = self._collect_gauges()
        edges += self._eval_watermarks(gauges, details)
        if self.changepoint:
            edges += self._eval_changepoints(gauges)
        if self.blackbox is not None:
            self._metrics_tick += 1
            if self._metrics_tick >= self.metrics_every_ticks:
                self._metrics_tick = 0
                try:
                    # snapshot() runs the registry drain hook, which
                    # writes the rate-limited "metrics" record
                    tel.registry.snapshot()
                except Exception:
                    pass
        with self._lock:
            self._ticks += 1
            self._tick_ns.append(time.perf_counter_ns() - t0)
        return edges

    # -- pillar (b): burn + watermarks ---------------------------------------
    def _divergence(self) -> Optional[Dict[str, Any]]:
        recorder = getattr(self.telemetry, "flight", None)
        if recorder is None:
            return None
        try:
            return recorder.tail_divergence()
        except Exception:
            return None

    def _eval_burn(self) -> List[Alert]:
        edges: List[Alert] = []
        for slo in self.telemetry.slos():
            fast = slo.burn_rate(self.fast_window_s)
            slow = slo.burn_rate()
            firing = (fast >= self.fast_burn_threshold
                      and slow >= self.slow_burn_threshold)
            evidence = {
                "slo": slo.name,
                "metric": slo.metric,
                "threshold_ms": slo.threshold_ms,
                "objective": slo.objective,
                "fast_window_s": self.fast_window_s,
                "slow_window_s": slo.window_s,
                "fast_burn": round(fast, 4),
                "slow_burn": round(slow, 4),
                "fast_burn_threshold": self.fast_burn_threshold,
                "slow_burn_threshold": self.slow_burn_threshold,
            }
            if firing:
                evidence["divergence"] = self._divergence()
            edges += self._set_condition(
                "slo_burn", f"slo:{slo.name}", firing, "page", evidence)
        return edges

    def _collect_gauges(self) -> Tuple[Dict[str, float], Dict[str, Any]]:
        """One flattened gauge namespace per tick, assembled from the
        live objects registered on the telemetry (pools, admission
        controllers, federations) plus the process arenas — each layer's
        ``watch_gauges()`` is the gauge source contract."""
        vals: Dict[str, float] = {}
        details: Dict[str, Any] = {}
        tel = self.telemetry
        breakers = quarantined = unrouteable = 0
        quarantined_urls: List[str] = []
        breaker_urls: List[str] = []
        pools = tel.pools() if hasattr(tel, "pools") else []
        for pool in pools:
            try:
                wg = pool.watch_gauges()
            except Exception:
                continue
            breakers += wg.get("breakers_open", 0)
            quarantined += wg.get("quarantined", 0)
            unrouteable += wg.get("unrouteable", 0)
            quarantined_urls += wg.get("quarantined_urls", [])
            breaker_urls += wg.get("breaker_open_urls", [])
        if pools:
            vals["pool.breakers_open"] = float(breakers)
            vals["pool.quarantined"] = float(quarantined)
            vals["pool.unrouteable"] = float(unrouteable)
            details["pool.quarantined"] = {"urls": quarantined_urls}
            details["pool.breakers_open"] = {"urls": breaker_urls}
        admitted = shed = 0.0
        ctrls = (tel.admission_controllers()
                 if hasattr(tel, "admission_controllers") else [])
        for ctrl, _scope in ctrls:
            try:
                wg = ctrl.watch_gauges()
            except Exception:
                continue
            admitted += wg.get("admitted_total", 0)
            shed += wg.get("shed_total", 0)
        if ctrls:
            prev = self._prev_admission
            self._prev_admission = (admitted, shed)
            if prev is not None:
                d_adm = max(admitted - prev[0], 0.0)
                d_shed = max(shed - prev[1], 0.0)
                denom = d_adm + d_shed
                vals["admission.shed_rate"] = (
                    d_shed / denom if denom > 0 else 0.0)
                details["admission.shed_rate"] = {
                    "admitted_delta": d_adm, "shed_delta": d_shed}
        cells_down = 0
        down_names: List[str] = []
        feds = tel.federations() if hasattr(tel, "federations") else []
        for fed, _scope in feds:
            try:
                wg = fed.watch_gauges()
            except Exception:
                continue
            cells_down += wg.get("cells_down", 0)
            down_names += wg.get("down_cells", [])
        if feds:
            vals["federation.cells_down"] = float(cells_down)
            details["federation.cells_down"] = {"cells": down_names}
        leased = total = 0
        import sys as _sys
        arena_mod = _sys.modules.get("client_tpu.arena")
        if arena_mod is not None:
            for arena in arena_mod.arenas():
                try:
                    stats = arena.stats()
                except Exception:
                    continue
                leased += stats.get("leased_bytes", 0)
                total += stats.get("total_bytes", 0)
            if total > 0:
                vals["arena.leased_fraction"] = leased / total
                details["arena.leased_fraction"] = {
                    "leased_bytes": leased, "total_bytes": total}
        return vals, details

    def _eval_watermarks(self, gauges: Dict[str, float],
                         details: Dict[str, Any]) -> List[Alert]:
        edges: List[Alert] = []
        for rule in self.watermarks:
            value = gauges.get(rule.key)
            if value is None:
                continue
            key = ("watermark", f"gauge:{rule.key}")
            active = key in self._active
            # hysteresis: an active alert resolves only below clear_level
            firing = (value >= rule.threshold if not active
                      else value >= rule.clear_level())
            evidence = {
                "rule": rule.name,
                "gauge": rule.key,
                "value": round(float(value), 6),
                "threshold": rule.threshold,
                "clear": rule.clear_level(),
            }
            detail = details.get(rule.key)
            if detail:
                evidence.update(detail)
            edges += self._set_condition(
                "watermark", f"gauge:{rule.key}", firing, rule.severity,
                evidence)
        return edges

    # -- pillar (c): changepoints --------------------------------------------
    def _stream_samples(self, gauges: Dict[str, float],
                        ) -> Dict[str, float]:
        samples: Dict[str, float] = {}
        tel = self.telemetry
        windows = (tel.stream_windows()
                   if hasattr(tel, "stream_windows") else {})
        for (metric, frontend), sketch in windows.items():
            if metric not in self._STREAM_METRICS:
                continue
            counts, total, _ = sketch.merged_recent(self.fast_window_s)
            if total < self.min_stream_count:
                continue
            samples[f"{metric}:{frontend}:p99"] = sketch.quantile_recent(
                0.99, self.fast_window_s)
        shed_rate = gauges.get("admission.shed_rate")
        if shed_rate is not None:
            samples["shed_rate"] = shed_rate
        return samples

    def _make_detector(self, stream: str) -> Cusum:
        # shed rate lives in [0, 1]: the ms-scale floor would deafen it
        abs_floor = 0.02 if stream == "shed_rate" else 0.5
        return Cusum(k=self.cusum_k, h=self.cusum_h,
                     warmup=self.cusum_warmup, abs_floor=abs_floor)

    def _eval_changepoints(self, gauges: Dict[str, float]) -> List[Alert]:
        edges: List[Alert] = []
        for stream, value in self._stream_samples(gauges).items():
            detector = self._detectors.get(stream)
            if detector is None:
                detector = self._detectors[stream] = \
                    self._make_detector(stream)
            baseline_mean = detector.mean
            baseline_sigma = (detector.sigma()
                              if detector.n >= detector.warmup else None)
            tripped = detector.update(value)
            if tripped:
                self._changepoint_trips += 1
                divergence = self._divergence()
                moved = (divergence["dominant"]
                         if divergence else "fleet_shift")
                evidence = {
                    "stream": stream,
                    "value": round(value, 4),
                    "baseline_mean": round(baseline_mean, 4),
                    "baseline_sigma": (round(baseline_sigma, 4)
                                       if baseline_sigma else None),
                    "divergence": divergence,
                    "moved": moved,
                }
                edges += self._set_condition(
                    "changepoint", f"changepoint:{stream}", True, "page",
                    evidence)
            else:
                # a changepoint is an event: the edge auto-resolves on the
                # first non-tripping tick (the detector re-warms, so a
                # persistent shift is re-learned, not re-alerted)
                edges += self._set_condition(
                    "changepoint", f"changepoint:{stream}", False, "page",
                    {})
        return edges

    # -- edge semantics ------------------------------------------------------
    def _set_condition(self, kind: str, source: str, firing: bool,
                       severity: str, evidence: Dict[str, Any],
                       ) -> List[Alert]:
        key = (kind, source)
        with self._lock:
            active = self._active.get(key)
            if firing and active is None:
                alert = Alert(kind, severity, source, evidence,
                              state="firing",
                              fired_unix=round(time.time(), 3))
                self._active[key] = alert
                self._fired[kind] = self._fired.get(kind, 0) + 1
                self._history.append(alert.as_dict())
            elif not firing and active is not None:
                del self._active[key]
                active.state = "resolved"
                active.resolved_unix = round(time.time(), 3)
                self._resolved[kind] = self._resolved.get(kind, 0) + 1
                self._history.append(active.as_dict())
                alert = active
            else:
                if active is not None and evidence:
                    active.evidence = evidence  # refresh, no re-emit
                return []
        self._emit(alert)
        return [alert]

    def _emit(self, alert: Alert) -> None:
        for sink in self.sinks:
            try:
                sink(alert)
            except Exception:
                pass
        if self.blackbox is not None:
            try:
                self.blackbox.append("alert", alert.as_dict())
            except Exception:
                pass
        if self.flight_marks:
            recorder = getattr(self.telemetry, "flight", None)
            if recorder is not None and hasattr(recorder, "mark"):
                try:
                    recorder.mark(
                        "watch", "alert", kind=alert.kind,
                        source=alert.source, severity=alert.severity,
                        state=alert.state)
                except Exception:
                    pass

    # -- read side -----------------------------------------------------------
    def active_alerts(self) -> List[Alert]:
        with self._lock:
            return list(self._active.values())

    def history(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._history)

    def stats(self) -> Dict[str, Any]:
        """JSON-pure accounting: the perf harness emits this (plus the
        active set) as the ``client_watch`` row block."""
        from .utils import sorted_percentile

        with self._lock:
            tick_ns = sorted(self._tick_ns)
            out: Dict[str, Any] = {
                "ticks": self._ticks,
                "tick_errors": self._tick_errors,
                "interval_s": self.interval_s,
                "alerts_fired": dict(self._fired),
                "alerts_resolved": dict(self._resolved),
                "alerts_active": len(self._active),
                "changepoint_trips": self._changepoint_trips,
            }
        out["alerts_fired_total"] = sum(out["alerts_fired"].values())
        out["alerts_resolved_total"] = sum(out["alerts_resolved"].values())
        if tick_ns:
            out["tick_ns"] = {
                "p50": round(sorted_percentile(tick_ns, 0.5), 1),
                "p99": round(sorted_percentile(tick_ns, 0.99), 1),
            }
        if self.blackbox is not None:
            out["blackbox"] = self.blackbox.stats()
        return out

    def snapshot(self) -> Dict[str, Any]:
        """The doctor's ``watch`` section: stats + active alerts + recent
        history + detector states, JSON-pure."""
        out = self.stats()
        with self._lock:
            out["active"] = [a.as_dict() for a in self._active.values()]
            out["recent"] = list(self._history)[-32:]
            out["detectors"] = {
                stream: det.state()
                for stream, det in sorted(self._detectors.items())
            }
        out["rules"] = {
            "burn": {
                "fast_window_s": self.fast_window_s,
                "fast_burn_threshold": self.fast_burn_threshold,
                "slow_burn_threshold": self.slow_burn_threshold,
                "slos": [slo.name for slo in self.telemetry.slos()],
            },
            "watermarks": [
                {"name": r.name, "gauge": r.key, "threshold": r.threshold,
                 "clear": r.clear_level(), "severity": r.severity}
                for r in self.watermarks
            ],
            "changepoint": {
                "enabled": self.changepoint,
                "k": self.cusum_k, "h": self.cusum_h,
                "warmup": self.cusum_warmup,
                "streams": sorted(self._detectors),
            },
        }
        return out


# -- process-global install (the dataplane pattern) ---------------------------
_WATCH: Optional[Watchtower] = None


def watchtower() -> Optional[Watchtower]:
    """The installed process-global watchtower, if any."""
    return _WATCH


def install_watchtower(tower: Optional[Watchtower]) -> Optional[Watchtower]:
    """Install (or clear, with None) the process-global watchtower;
    returns the previous one so scoped users (perf runs, tests) can
    restore it."""
    global _WATCH
    previous = _WATCH
    _WATCH = tower
    return previous


def enable_watchtower(telemetry, **kwargs) -> Watchtower:
    """Create a :class:`Watchtower` on ``telemetry``, install it
    process-globally and start its background thread; returns it."""
    tower = Watchtower(telemetry, **kwargs)
    install_watchtower(tower)
    return tower.start()
