"""Batchable matmul model — the dynamic-batcher's showcase fixture.

``batched_matmul``: X FP32[-1, 64] @ W[64, 16] -> Y FP32[-1, 16], with
``max_batch_size`` declared so the core's DynamicBatcher coalesces
concurrent [1, 64] requests into one [k, 64] execution. On the MXU a
[32, 64]x[64, 16] costs barely more than [1, 64]x[64, 16] — the entire
point of batching — and the jitted matmul compiles once per distinct k
(bounded by max_batch_size).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List

import numpy as np

from .base import Model, TensorSpec


class BatchedMatMulModel(Model):
    name = "batched_matmul"
    platform = "jax"
    max_batch_size = 32

    IN_DIM = 64
    OUT_DIM = 16

    def __init__(self, seed: int = 0, delay_s: float = 0.0):
        """``delay_s`` simulates per-EXECUTION cost (not per-row): tests use
        it to make coalescing observable in wall time."""
        super().__init__()
        self._delay_s = delay_s
        self._lock = threading.Lock()
        self._w = None
        self._fn = None
        rng = np.random.default_rng(seed)
        self._w_np = rng.standard_normal(
            (self.IN_DIM, self.OUT_DIM)).astype(np.float32)
        self.executed_batches: List[int] = []  # instrumentation for tests

    def inputs(self) -> List[TensorSpec]:
        return [TensorSpec("X", "FP32", [-1, self.IN_DIM])]

    def outputs(self) -> List[TensorSpec]:
        return [TensorSpec("Y", "FP32", [-1, self.OUT_DIM])]

    def _ensure_built(self):
        with self._lock:
            if self._fn is None:
                import jax
                import jax.numpy as jnp

                self._w = jnp.asarray(self._w_np)
                self._fn = jax.jit(lambda x, w: x @ w)

    def execute(self, inputs: Dict[str, np.ndarray], parameters: Dict[str, Any]):
        self._ensure_built()
        import time

        x = np.asarray(inputs["X"], dtype=np.float32)
        with self._lock:
            self.executed_batches.append(int(x.shape[0]))
        if self._delay_s:
            time.sleep(self._delay_s)
        y = np.asarray(self._fn(x, self._w))
        return {"Y": y}
