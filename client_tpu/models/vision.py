"""Flagship vision classifier: the ``densenet_onnx`` fixture contract on XLA.

The reference's image_client targets a ``densenet_onnx`` model served by
tritonserver (image_client.py: parse_model :60, preprocess :154, postprocess
:196); the model itself is an ONNX artifact the client repo doesn't contain.
Here the contract — input ``data_0`` FP32 [3,224,224] (CHW), output ``fc6_1``
FP32 [1000,1,1], classification labels — is served by a TPU-first flax CNN:

- bfloat16 activations/matmuls (MXU-native), float32 params
- NHWC layout internally (TPU convolution-friendly); the CHW wire format of
  the fixture is transposed once at the boundary
- dense-block-style feature reuse, global average pooling (any input HW)
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

from .base import Model, TensorSpec


def _build_flax_model(num_classes: int, width: int = 32, stages=(2, 2, 2)):
    import flax.linen as nn
    import jax.numpy as jnp

    class ConvBlock(nn.Module):
        features: int

        @nn.compact
        def __call__(self, x):
            x = nn.Conv(self.features, (3, 3), padding="SAME", use_bias=False,
                        dtype=jnp.bfloat16)(x)
            x = nn.GroupNorm(num_groups=8, dtype=jnp.bfloat16)(x)
            return nn.relu(x)

    class DenseStage(nn.Module):
        """Dense-block flavor: each layer sees the concat of all prior maps."""

        growth: int
        layers: int

        @nn.compact
        def __call__(self, x):
            for _ in range(self.layers):
                y = ConvBlock(self.growth)(x)
                x = jnp.concatenate([x, y], axis=-1)
            return x

    class DenseNetish(nn.Module):
        num_classes: int
        width: int
        stages: tuple = (2, 2, 2)

        @nn.compact
        def __call__(self, x):  # x: [N, H, W, C] bf16
            x = nn.Conv(self.width, (7, 7), strides=(2, 2), padding="SAME",
                        use_bias=False, dtype=jnp.bfloat16)(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
            for i, layers in enumerate(self.stages):
                x = DenseStage(growth=self.width * (2**min(i, 2)), layers=layers)(x)
                # transition: 1x1 squeeze + stride-2 pool
                x = ConvBlock(self.width * (2**min(i, 2)))(x)
                x = nn.avg_pool(x, (2, 2), strides=(2, 2))
            x = jnp.mean(x, axis=(1, 2))  # global average pool
            x = nn.Dense(self.num_classes, dtype=jnp.bfloat16)(x)
            return x.astype(jnp.float32)

    return DenseNetish(num_classes=num_classes, width=width, stages=tuple(stages))


class ImagePreprocessModel(Model):
    """``preprocess``: raw UINT8 HWC image -> normalized FP32 CHW [3,224,224].

    The ensemble front stage (reference: the DALI/preprocess member of
    ensemble_image_client's pipeline): nearest-neighbor resize + INCEPTION
    scaling fused on-device via the Pallas normalize kernel.
    """

    name = "preprocess"

    def inputs(self) -> List[TensorSpec]:
        return [TensorSpec("raw_image", "UINT8", [-1, -1, 3])]

    def outputs(self) -> List[TensorSpec]:
        return [TensorSpec("preprocessed", "FP32", [3, 224, 224])]

    def execute(self, inputs: Dict[str, np.ndarray], parameters: Dict[str, Any]):
        from ..ops import preprocess_image

        # resize + INCEPTION normalize + CHW layout: one compiled program
        arr = preprocess_image(
            np.asarray(inputs["raw_image"]), 224, 224,
            scale=2.0 / 255.0, shift=-1.0,
        )
        return {"preprocessed": np.ascontiguousarray(arr)}


class DenseNetModel(Model):
    """Server-side vision model with the densenet_onnx wire contract."""

    name = "densenet_onnx"
    platform = "jax_flax"
    max_batch_size = 0  # fixture contract: one CHW image per request

    # stage depths: "lite" is the CI/protocol-testing default; "121" is the
    # densenet-121 layout (6/12/24/16 dense layers) for real-chip rounds
    ARCHS = {"lite": (2, 2, 2), "121": (6, 12, 24, 16)}

    def __init__(
        self,
        num_classes: int = 1000,
        width: int = 32,
        seed: int = 0,
        tensor_parallel: int = 1,
        arch: str = "lite",
    ):
        """``tensor_parallel > 1`` shards parameter output-feature axes over a
        (1, tp) device mesh; XLA inserts the collectives (serving-side scale,
        no client change). ``arch``: "lite" (default) or "121"
        (densenet-121 stage depths — budget for the compile on CPU)."""
        super().__init__()
        if arch not in self.ARCHS:
            raise ValueError(f"arch must be one of {sorted(self.ARCHS)}")
        self._num_classes = num_classes
        self._width = width
        self._seed = seed
        self._tensor_parallel = tensor_parallel
        self._stages = self.ARCHS[arch]
        self._lock = threading.Lock()
        self._module = None
        self._params = None
        self._jit_fn = None
        self._labels = [f"class_{i}" for i in range(num_classes)]

    def inputs(self) -> List[TensorSpec]:
        return [TensorSpec("data_0", "FP32", [3, 224, 224])]

    def outputs(self) -> List[TensorSpec]:
        return [TensorSpec("fc6_1", "FP32", [self._num_classes, 1, 1])]

    def labels(self) -> Optional[List[str]]:
        return self._labels

    # -- lazy build (first inference pays init+compile once) ----------------
    def _ensure_built(self):
        with self._lock:
            if self._jit_fn is not None:
                return
            import jax
            import jax.numpy as jnp

            self._module = _build_flax_model(
                self._num_classes, self._width, self._stages
            )
            rng = jax.random.PRNGKey(self._seed)
            dummy = jnp.zeros((1, 224, 224, 3), jnp.bfloat16)
            self._params = self._module.init(rng, dummy)

            if self._tensor_parallel > 1:
                from jax.sharding import Mesh

                from ..parallel import shard_params

                devices = jax.devices()
                tp = min(self._tensor_parallel, len(devices))
                # (1, tp): serve-time batch stays whole, weights shard on
                # 'model' (make_mesh's dp-leaning factorization fits training)
                mesh = Mesh(
                    np.array(devices[:tp]).reshape(1, tp), ("data", "model")
                )
                self._params = shard_params(self._params, mesh)

            @jax.jit
            def forward(params, chw_batch):
                # wire contract is CHW float32; go NHWC bf16 for the MXU
                x = jnp.transpose(chw_batch, (0, 2, 3, 1)).astype(jnp.bfloat16)
                return self._module.apply(params, x)

            self._jit_fn = forward

    def forward_fn(self):
        """(jittable_fn, params) for direct embedding (entry(), parallel)."""
        self._ensure_built()
        return self._jit_fn, self._params

    def execute(self, inputs: Dict[str, np.ndarray], parameters: Dict[str, Any]):
        self._ensure_built()
        import jax.numpy as jnp

        arr = inputs["data_0"]
        x = jnp.asarray(arr).reshape((1, 3) + tuple(arr.shape[-2:]))
        logits = self._jit_fn(self._params, x)  # [1, num_classes]
        return {"fc6_1": logits.reshape(self._num_classes, 1, 1)}
