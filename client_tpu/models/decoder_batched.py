"""Slot-based sequence batcher: concurrent decodes share one dispatch.

tritonserver's *sequence batcher* (direct mode) assigns each live sequence
a batch slot and runs every slot's next step in a single model execution —
the client repo exposes it through the same sequence_id/start/end controls
the ``decoder_lm`` fixture serves (SURVEY §5 long-context/sequence).
``decoder_lm`` executes each sequence's step as its own device dispatch;
at S concurrent sequences that is S dispatches per token — exactly the
regime batching exists for, since an [S, ...] step costs barely more than
a [1, ...] step until S fills the MXU tile.

``decoder_lm_batched`` is the TPU-first version: per-slot KV caches live
stacked on device ([slots, heads, max_len, head_dim] per layer), a
coalescer thread gathers whatever sequence requests are in flight inside a
~2 ms window, and ONE jitted batched step (``jax.vmap`` of the decoder's
single-sequence step — the identical math, so tokens are bit-comparable)
advances them all. Slots whose sequence has no pending request this round
ride along masked: their cache/pos updates are discarded by a
``jnp.where`` select, which keeps the executable static-shape — the same
compile-once property the single-sequence decoder has. Prompts longer than
one token naturally lockstep: each coalescer round consumes the next token
of every gathered request, so two sequences prefilling together share
every dispatch.

Weights come from a composed TinyDecoderModel (same seed ⇒ greedy tokens
match the unbatched fixture token-for-token — pinned by the tests).
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future, InvalidStateError
from concurrent.futures import TimeoutError as FuturesTimeout
from typing import Any, Dict, List

import numpy as np

from .base import Model, TensorSpec
from .decoder import TinyDecoderModel


class _SeqRequest:
    __slots__ = ("seq_id", "tokens", "start", "end", "future")

    def __init__(self, seq_id, tokens, start, end):
        self.seq_id = seq_id
        self.tokens = tokens  # list of ints, consumed one per round
        self.start = start
        self.end = end
        self.future: Future = Future()

    # The caller may cancel() the future (120s timeout) at any moment —
    # set_result/set_exception on a cancelled future raises
    # InvalidStateError, and an unguarded raise inside the worker's
    # resolution loop would strand every later request in the window.
    def resolve(self, value) -> None:
        try:
            if not self.future.done():
                self.future.set_result(value)
        except InvalidStateError:
            pass  # caller cancelled between the check and the set

    def fail(self, exc: BaseException) -> None:
        try:
            if not self.future.done():
                self.future.set_exception(exc)
        except InvalidStateError:
            pass


class BatchedDecoderModel(Model):
    """``decoder_lm_batched``: the decoder_lm contract, slot-batched."""

    name = "decoder_lm_batched"
    platform = "jax"
    max_batch_size = 0
    stateful = True

    def __init__(self, seed: int = 0, slots: int = 8,
                 max_delay_s: float = 0.002, attention_impl: str = "einsum",
                 idle_ttl_s: float = 300.0):
        super().__init__()
        self._decoder = TinyDecoderModel(seed=seed,
                                         attention_impl=attention_impl)
        self.slots = int(slots)
        self._max_delay_s = max_delay_s
        # Idle-sequence reaper TTL (reference semantics:
        # max_sequence_idle_microseconds in tritonserver's sequence
        # batcher). Must exceed the 120 s caller timeout so a slot whose
        # window is merely slow is never reclaimed under an in-flight step.
        self._idle_ttl_s = float(idle_ttl_s)
        self._last_seen: Dict[Any, float] = {}
        self._lock = threading.Lock()
        self._built = False
        self._queue: "queue.Queue[_SeqRequest]" = queue.Queue(maxsize=1024)
        self._closed = False
        self._carry: List[_SeqRequest] = []
        # observability for tests/tuning: rounds executed per batch width
        self.batch_histogram: Dict[int, int] = {}
        self._worker = None  # started lazily with the first build

    def inputs(self) -> List[TensorSpec]:
        return [TensorSpec("TOKENS", "INT32", [1, -1])]

    def outputs(self) -> List[TensorSpec]:
        return [
            TensorSpec("LOGITS", "FP32", [1, self._decoder.VOCAB]),
            TensorSpec("NEXT_TOKEN", "INT32", [1, 1]),
        ]

    # -- compiled pieces -----------------------------------------------------
    def _ensure_built(self):
        with self._lock:
            if self._built:
                return
            self._decoder._ensure_built()
            import jax
            import jax.numpy as jnp

            dec = self._decoder
            S = self.slots
            Dh = dec.D_MODEL // dec.HEADS
            step1 = dec._step_fn  # (params, caches, token, pos) per sequence
            vstep = jax.vmap(step1, in_axes=(None, 0, 0, 0))

            def batched_step(params, caches, tokens, pos, active):
                logits, new_caches = vstep(params, caches, tokens, pos)

                def sel(new, old):
                    mask = active.reshape((-1,) + (1,) * (new.ndim - 1))
                    return jnp.where(mask, new, old)

                caches = jax.tree_util.tree_map(sel, new_caches, caches)
                return logits, caches

            self._batched_step = jax.jit(batched_step)
            self._caches = [
                {
                    "k": jnp.zeros((S, dec.HEADS, dec.MAX_LEN, Dh),
                                   jnp.bfloat16),
                    "v": jnp.zeros((S, dec.HEADS, dec.MAX_LEN, Dh),
                                   jnp.bfloat16),
                }
                for _ in range(dec.LAYERS)
            ]
            # positions live HOST-side (0 on start, +1 per active token —
            # fully derivable without a device readback) and ship to the
            # device each round alongside the token vector; carrying them
            # on-device would cost a blocking readback per request in
            # _run_window, the exact per-dispatch RTT the batcher
            # amortizes (~60 ms each on a tunneled chip)
            self._pos = np.zeros((S,), np.int32)
            self._slot_of: Dict[Any, int] = {}
            self._free = list(range(S))
            self._worker = threading.Thread(
                target=self._run, name="sequence-batcher", daemon=True)
            self._worker.start()
            self._built = True

    # -- serving (caller side) ----------------------------------------------
    def execute(self, inputs: Dict[str, np.ndarray],
                parameters: Dict[str, Any]):
        self._ensure_built()
        seq_id = parameters.get("sequence_id", 0)
        if not seq_id:
            raise ValueError("decoder_lm_batched requires a sequence_id")
        start = bool(parameters.get("sequence_start", False))
        end = bool(parameters.get("sequence_end", False))
        tokens = np.asarray(inputs["TOKENS"]).reshape(-1).astype(np.int64)
        if tokens.size == 0:
            raise ValueError("empty prompt")
        if np.any(tokens < 0) or np.any(tokens >= self._decoder.VOCAB):
            raise ValueError(f"tokens out of range [0, {self._decoder.VOCAB})")
        if not start and len(tokens) != 1:
            raise ValueError("continuation requests carry exactly one token")
        if self._closed:
            raise ValueError("model is shutting down")
        req = _SeqRequest(seq_id, [int(t) for t in tokens], start, end)
        try:
            # bounded wait: with a wedged worker the queue fills, and an
            # unbounded put() would hang callers before the future timeout
            # below ever ran — overload must surface as a typed 503
            self._queue.put(req, timeout=30)
        except queue.Full:
            from ..server.core import InferError

            raise InferError(
                "sequence batcher queue full (worker stalled?)", 503
            ) from None
        if self._closed:
            # unload() raced us: the worker may already be past its
            # sentinel, leaving this request stranded behind it — fail it
            # here (the worker wins harmlessly if it got there first)
            req.fail(ValueError("model is shutting down"))
        try:
            logits = req.future.result(timeout=120)
        except FuturesTimeout:
            # the worker is wedged or the dispatch is pathologically slow;
            # the caller is gone either way, so surface a gateway-timeout
            # rather than an untyped 500. The slot is NOT freed here — the
            # window may still be in flight and a new sequence claiming the
            # slot would share its cache; the window's own error path (or
            # sequence_end) reclaims it.
            req.future.cancel()
            from ..server.core import InferError

            raise InferError(
                "batched decode timed out after 120s", 504) from None
        logits_np = np.asarray(logits, dtype=np.float32).reshape(
            1, self._decoder.VOCAB)
        return {
            "LOGITS": logits_np,
            "NEXT_TOKEN": np.array([[int(logits_np.argmax())]], dtype=np.int32),
        }

    def live_sequences(self) -> int:
        self._ensure_built()
        with self._lock:
            return len(self._slot_of)

    def unload(self) -> None:
        self._closed = True
        self._queue.put(None)
        if self._worker is not None:
            self._worker.join(timeout=10)
        # fail anything that slipped in behind the sentinel (the worker has
        # exited; nothing else will ever resolve those futures)
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if req is not None:
                req.fail(ValueError("model is shutting down"))
        super().unload()

    # -- coalescer worker ----------------------------------------------------
    def _collect(self) -> List[_SeqRequest]:
        """One window: at most one request per sequence (two requests on a
        sequence must observe each other's cache updates, so the second
        waits for the next round — the reference sequence batcher
        serializes per CORRID the same way)."""
        window, seen, still_carried = [], set(), []
        for req in self._carry:
            if req.seq_id in seen:
                still_carried.append(req)  # FIFO within a sequence
            else:
                window.append(req)
                seen.add(req.seq_id)
        self._carry = still_carried
        if not window:
            first = self._queue.get()
            if first is None:
                return []
            window.append(first)
            seen.add(first.seq_id)
        deadline = time.monotonic() + self._max_delay_s
        while len(window) < self.slots:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                nxt = self._queue.get(timeout=remaining)
            except queue.Empty:
                break
            if nxt is None:
                self._queue.put(None)
                break
            if nxt.seq_id in seen:
                # serialize per CORRID but KEEP collecting: a fast client's
                # back-to-back request must not shut other sequences out of
                # this round
                self._carry.append(nxt)
                continue
            window.append(nxt)
            seen.add(nxt.seq_id)
        return window

    def _admit(self, req: _SeqRequest) -> int:
        """Resolve the request to a slot (allocating on sequence_start)."""
        with self._lock:
            if req.start:
                if req.seq_id in self._slot_of:
                    slot = self._slot_of[req.seq_id]  # restart in place
                elif self._free:
                    slot = self._free.pop()
                    self._slot_of[req.seq_id] = slot
                else:
                    raise ValueError(
                        f"no free sequence slot (capacity {self.slots}); "
                        "end a sequence first")
                self._last_seen[req.seq_id] = time.monotonic()
                return slot
            slot = self._slot_of.get(req.seq_id)
            if slot is None:
                raise ValueError(
                    f"sequence {req.seq_id} has no live state "
                    "(missing sequence_start?)")
            self._last_seen[req.seq_id] = time.monotonic()
            return slot

    def _reap_idle(self, exclude) -> None:
        """Free slots whose sequence has been idle past the TTL.

        Covers the 120 s-timeout abandonment path: a client that times out
        mid-sequence and walks away would otherwise hold one of ``slots``
        forever (only a same-id restart or unload reclaimed it). Sequences
        with a request in the current window or carried for the next round
        are excluded — they are active by definition.
        """
        now = time.monotonic()
        with self._lock:
            for seq_id, last in list(self._last_seen.items()):
                if seq_id in exclude:
                    continue
                if now - last > self._idle_ttl_s:
                    self._free_slot(seq_id)

    def _run(self) -> None:
        while True:
            window = self._collect()
            if not window:
                return
            try:
                self._run_window(window)
            except Exception as e:  # the worker thread must NEVER die — a
                # dead coalescer wedges every future request on the model
                for req in window:
                    req.fail(e)

    def _run_window(self, window: List[_SeqRequest]) -> None:
        import jax.numpy as jnp

        # reap BEFORE admitting so a full house of abandoned sequences
        # frees up for this window's sequence_start requests
        self._reap_idle(
            exclude={req.seq_id for req in window}
            | {r.seq_id for r in self._carry})

        dec = self._decoder
        active_reqs: List[tuple] = []  # (req, slot)
        for req in window:
            try:
                slot = self._admit(req)
            except Exception as e:
                req.fail(e)
                continue
            if req.start:
                # zero pos; cache rows are fully overwritten as the
                # prompt streams in, and masked reads never see slots
                # beyond pos, so stale cache content is harmless
                self._pos[slot] = 0
            pos_here = int(self._pos[slot])
            if pos_here + len(req.tokens) > dec.MAX_LEN:
                req.fail(ValueError(
                    f"sequence longer than max_len {dec.MAX_LEN}"))
                with self._lock:
                    self._free_slot(req.seq_id)
                continue
            active_reqs.append((req, slot))

        # lockstep rounds: each round consumes ONE token from every
        # request that still has tokens left (prompts prefill together)
        last_logits: Dict[int, Any] = {}
        try:
            while any(req.tokens for req, _ in active_reqs):
                tokens = np.zeros((self.slots,), np.int32)
                active = np.zeros((self.slots,), bool)
                for req, slot in active_reqs:
                    if req.tokens:
                        tokens[slot] = req.tokens.pop(0)
                        active[slot] = True
                # snapshot pos: device_put may alias the host buffer
                # (CPU zero-copy) or read it after dispatch returns
                # (ImmutableUntilTransferCompletes), so handing JAX
                # self._pos itself and then mutating it in place races
                # the in-flight step — the round-3 nondeterminism
                logits, self._caches = self._batched_step(
                    dec._params, self._caches,
                    jnp.asarray(tokens), jnp.asarray(self._pos.copy()),
                    jnp.asarray(active))
                self._pos[active] += 1
                self.batch_histogram[int(active.sum())] = (
                    self.batch_histogram.get(int(active.sum()), 0) + 1)
                for req, slot in active_reqs:
                    if active[slot]:
                        last_logits[slot] = logits[slot]
        except Exception as e:  # a failed dispatch must not strand callers
            for req, _ in active_reqs:
                req.fail(e)
                # a failed step ends the sequence regardless of req.end:
                # the client has no valid continuation state (the cache may
                # be partially updated), and keeping the slot would leak
                # capacity one failed window at a time
                with self._lock:
                    self._free_slot(req.seq_id)
            return

        for req, slot in active_reqs:
            if req.end:
                with self._lock:
                    self._free_slot(req.seq_id)
            if slot in last_logits:
                req.resolve(last_logits[slot])
            else:
                req.fail(ValueError("request executed no decode step"))

    def _free_slot(self, seq_id) -> None:
        slot = self._slot_of.pop(seq_id, None)
        self._last_seen.pop(seq_id, None)
        if slot is not None:
            self._free.append(slot)
