"""Fixture-contract models as jitted JAX programs.

Contracts mirror the tritonserver QA fixture models the reference examples
target (SURVEY.md §2.4): ``simple`` (INT32 sum/diff), ``simple_identity``
(BYTES passthrough), ``custom_identity_int32`` (configurable-delay identity,
used by timeout tests), ``simple_sequence`` (stateful per-sequence
accumulator), ``repeat_int32`` (decoupled N-response streamer).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Iterable, List

import numpy as np

from .base import Model, TensorSpec


def _jit_add_sub():
    import jax

    @jax.jit
    def add_sub(a, b):
        return a + b, a - b

    return add_sub


class AddSubModel(Model):
    """``simple``: INPUT0,INPUT1 INT32[1,16] -> OUTPUT0=sum, OUTPUT1=diff."""

    name = "simple"

    def __init__(self, batch_dim: int = 1, width: int = 16):
        super().__init__()
        self._shape = [batch_dim, width]
        self._fn = None
        self._lock = threading.Lock()

    def inputs(self) -> List[TensorSpec]:
        return [
            TensorSpec("INPUT0", "INT32", list(self._shape)),
            TensorSpec("INPUT1", "INT32", list(self._shape)),
        ]

    def outputs(self) -> List[TensorSpec]:
        return [
            TensorSpec("OUTPUT0", "INT32", list(self._shape)),
            TensorSpec("OUTPUT1", "INT32", list(self._shape)),
        ]

    def execute(self, inputs, parameters):
        with self._lock:
            if self._fn is None:
                self._fn = _jit_add_sub()
        s, d = self._fn(inputs["INPUT0"], inputs["INPUT1"])
        # returned as live jax.Arrays: the tpu-shm response path pins them
        # on-device; wire paths materialize to host at serialization time
        return {"OUTPUT0": s, "OUTPUT1": d}


class StringAddSubModel(Model):
    """``simple_string``: BYTES-encoded integers in, sum/diff as BYTES out."""

    name = "simple_string"

    def inputs(self):
        return [
            TensorSpec("INPUT0", "BYTES", [1, 16]),
            TensorSpec("INPUT1", "BYTES", [1, 16]),
        ]

    def outputs(self):
        return [
            TensorSpec("OUTPUT0", "BYTES", [1, 16]),
            TensorSpec("OUTPUT1", "BYTES", [1, 16]),
        ]

    def execute(self, inputs, parameters):
        a = np.vectorize(int)(inputs["INPUT0"]).astype(np.int32)
        b = np.vectorize(int)(inputs["INPUT1"]).astype(np.int32)
        to_bytes = np.vectorize(lambda v: str(int(v)).encode(), otypes=[np.object_])
        return {"OUTPUT0": to_bytes(a + b), "OUTPUT1": to_bytes(a - b)}


class IdentityModel(Model):
    """``simple_identity`` / ``custom_identity_int32``: passthrough.

    ``delay_s`` simulates a slow model for client/stream timeout tests
    (reference: client_timeout_test.cc vs custom_identity_int32).
    """

    def __init__(
        self,
        name: str = "simple_identity",
        datatype: str = "BYTES",
        input_name: str = "INPUT0",
        output_name: str = "OUTPUT0",
        delay_s: float = 0.0,
    ):
        super().__init__()
        self.name = name
        self._datatype = datatype
        self._input_name = input_name
        self._output_name = output_name
        self.delay_s = delay_s

    def inputs(self):
        return [TensorSpec(self._input_name, self._datatype, [-1, -1])]

    def outputs(self):
        return [TensorSpec(self._output_name, self._datatype, [-1, -1])]

    def execute(self, inputs, parameters):
        if self.delay_s:
            time.sleep(self.delay_s)
        arr = inputs[self._input_name]
        if arr.dtype != np.object_:
            # route through XLA so the data path is exercised on-device; the
            # result stays a jax.Array for the tpu-shm zero-copy response path
            import jax.numpy as jnp

            arr = jnp.asarray(arr)
        return {self._output_name: arr}


class SequenceAccumulatorModel(Model):
    """``simple_sequence``: per-sequence running INT32 accumulator.

    Control semantics follow the fixture: ``sequence_start`` resets the
    accumulator, every request adds its input value, the response carries the
    running total, ``sequence_end`` drops the sequence state.
    """

    name = "simple_sequence"
    stateful = True

    def __init__(self):
        super().__init__()
        self._state: Dict[Any, int] = {}
        self._lock = threading.Lock()

    def inputs(self):
        return [TensorSpec("INPUT", "INT32", [1, 1])]

    def outputs(self):
        return [TensorSpec("OUTPUT", "INT32", [1, 1])]

    def execute(self, inputs, parameters):
        seq_id = parameters.get("sequence_id", 0)
        start = parameters.get("sequence_start", False)
        end = parameters.get("sequence_end", False)
        if not seq_id:
            raise ValueError("simple_sequence requires a sequence_id")
        value = int(np.asarray(inputs["INPUT"]).reshape(-1)[0])
        with self._lock:
            acc = 0 if start else self._state.get(seq_id, 0)
            acc += value
            if end:
                self._state.pop(seq_id, None)
            else:
                self._state[seq_id] = acc
        return {"OUTPUT": np.array([[acc]], dtype=np.int32)}


class RepeatModel(Model):
    """``repeat_int32``: decoupled — emit one response per input element.

    Inputs: IN (INT32[-1]), DELAY (UINT32[-1], per-response delay in ms),
    WAIT (UINT32[1], initial wait in ms). Output: OUT (INT32[1]) streamed
    len(IN) times, plus IDX (UINT32[1]) with the response index.
    """

    name = "repeat_int32"
    decoupled = True

    def inputs(self):
        return [
            TensorSpec("IN", "INT32", [-1]),
            TensorSpec("DELAY", "UINT32", [-1], optional=True),
            TensorSpec("WAIT", "UINT32", [1], optional=True),
        ]

    def outputs(self):
        return [TensorSpec("OUT", "INT32", [1]), TensorSpec("IDX", "UINT32", [1])]

    def execute(self, inputs, parameters):
        raise ValueError("repeat_int32 is a decoupled model; use streaming infer")

    def execute_decoupled(self, inputs, parameters) -> Iterable[Dict[str, np.ndarray]]:
        values = np.asarray(inputs["IN"]).reshape(-1)
        delays = np.asarray(inputs.get("DELAY", np.zeros(len(values), np.uint32))).reshape(-1)
        wait = int(np.asarray(inputs.get("WAIT", np.zeros(1, np.uint32))).reshape(-1)[0])
        if wait:
            time.sleep(wait / 1000.0)
        for idx, v in enumerate(values):
            if idx < len(delays) and delays[idx]:
                time.sleep(int(delays[idx]) / 1000.0)
            yield {
                "OUT": np.array([v], dtype=np.int32),
                "IDX": np.array([idx], dtype=np.uint32),
            }


def default_model_zoo() -> List[Model]:
    """The fixture set every test/example expects to find on the server."""
    from .batched import BatchedMatMulModel
    from .chain import (
        ChainEmbedModel,
        ChainFusedModel,
        ChainRerankModel,
        ChainTokenizeModel,
    )
    from .decoder import TinyDecoderModel
    from .decoder_batched import BatchedDecoderModel
    from .decoder_prefill import PrefillDecoderModel
    from .disagg import DisaggPrefillModel, KvDecodeModel
    from .generate import TinyGenerateModel

    decoder = TinyDecoderModel()
    return [
        BatchedMatMulModel(),
        AddSubModel(),
        StringAddSubModel(),
        IdentityModel("simple_identity", "BYTES"),
        IdentityModel("custom_identity_int32", "INT32", delay_s=0.0),
        IdentityModel("identity_fp32", "FP32"),
        IdentityModel("identity_bf16", "BF16"),
        IdentityModel("identity_fp16", "FP16"),
        IdentityModel("identity_int8", "INT8"),
        SequenceAccumulatorModel(),
        RepeatModel(),
        decoder,
        TinyGenerateModel(decoder=decoder),
        BatchedDecoderModel(),
        # stateless batched prompt scoring (builds lazily): the sharded
        # scatter-gather client's batch-axis targets (client_tpu/shard.py)
        PrefillDecoderModel(tp=False),
        PrefillDecoderModel(tp=True),
        # disaggregated prefill/decode pair (client_tpu/disagg.py): KV
        # export + decode-from-handed-off-KV, sharing the zoo decoder's
        # weights so the split stream is bit-exact vs tiny_lm_generate
        DisaggPrefillModel(decoder=decoder),
        KvDecodeModel(decoder=decoder),
        # the pipeline chain (client_tpu/pipeline.py): three stages plus
        # the fused reference, all over one shared ChainCore so DAG runs
        # are bit-exact vs the single-model call
        ChainTokenizeModel(),
        ChainEmbedModel(),
        ChainRerankModel(),
        ChainFusedModel(),
    ]
