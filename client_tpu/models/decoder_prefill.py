"""Stateless batched prompt scoring over the decoder fixtures.

``decoder_lm`` serves one sequence per request behind the v2 sequence API —
the right contract for incremental decode, but useless as a scatter-gather
target: a sharded logical request must be stateless (any shard may land on
any pinned replica with no prior server-side state) and must carry an axis
the client can split. This module is that contract:

- ``decoder_lm_prefill``: TOKENS INT32 ``[-1, T]`` (a batch of equal-length
  prompts) -> LOGITS FP32 ``[-1, VOCAB]`` + NEXT_TOKEN INT32 ``[-1, 1]``,
  each row scored independently by running the decoder's compiled
  single-token step over the prompt with a fresh KV cache — the SAME step
  function ``decoder_lm`` serves, so row b's logits are bit-identical to
  scoring that prompt as a one-shot sequence.
- ``decoder_lm_tp_prefill``: the same contract over ``decoder_lm_tp``'s
  mesh-sharded step (Megatron-style head-sharded attention, see
  models/decoder_tp.py). TPDecoderModel's guarantee is BIT-equality with
  the single-device decoder, so the tp-prefill replica fleet is
  bit-comparable against a local single-process ``decoder_lm_prefill``
  reference — exactly the exactness oracle the sharded scatter-gather
  client (client_tpu/shard.py) is verified against: rows sharded across N
  tp replicas and gathered must equal the reference batch, bit for bit.

Rows are independent by construction (fresh cache per row), which is what
makes the batch axis a legal ``ShardLayout`` axis: splitting [B, T] into
contiguous row blocks and concatenating the per-shard [b_i, VOCAB] logits
reassociates nothing.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict, List, Optional

import numpy as np

from .base import Model, TensorSpec
from .decoder import TinyDecoderModel
from .decoder_tp import TPDecoderModel


class PrefillDecoderModel(Model):
    """``decoder_lm_prefill`` / ``decoder_lm_tp_prefill``: batched
    stateless prompt scoring (one fresh-cache decode per row).

    ``mesh``/``axis``/``tp_degree`` pass through to
    :class:`TPDecoderModel` so a multi-replica *in-process* test topology
    can give each replica a disjoint device slice. TP executions are
    additionally serialized by a process-wide lock: two replica servers
    hosted in ONE process (the test/bench topology) would otherwise run
    two SPMD programs concurrently over the same virtual devices and
    stall XLA's collective rendezvous — real deployments run one replica
    per process and never contend."""

    platform = "jax"
    max_batch_size = 0
    stateful = False

    _TP_EXEC_LOCK = threading.Lock()

    def __init__(self, tp: bool = False, seed: int = 0, mesh=None,
                 axis: str = "model", tp_degree: Optional[int] = None):
        super().__init__()
        self._tp = tp
        self._inner = (
            TPDecoderModel(seed=seed, tp=tp_degree, mesh=mesh, axis=axis)
            if tp else TinyDecoderModel(seed=seed))
        self.name = "decoder_lm_tp_prefill" if tp else "decoder_lm_prefill"

    def inputs(self) -> List[TensorSpec]:
        return [TensorSpec("TOKENS", "INT32", [-1, -1])]

    def outputs(self) -> List[TensorSpec]:
        return [
            TensorSpec("LOGITS", "FP32", [-1, self._inner.VOCAB]),
            TensorSpec("NEXT_TOKEN", "INT32", [-1, 1]),
        ]

    def execute(self, inputs: Dict[str, np.ndarray],
                parameters: Dict[str, Any]) -> Dict[str, np.ndarray]:
        inner = self._inner
        inner._ensure_built()
        tokens = np.asarray(inputs["TOKENS"])
        if tokens.ndim != 2 or tokens.shape[1] < 1:
            raise ValueError(
                f"TOKENS must be [batch, prompt_len >= 1], got "
                f"{list(tokens.shape)}")
        if tokens.shape[1] > inner.MAX_LEN:
            raise ValueError(
                f"prompt longer than max_len {inner.MAX_LEN}")
        tokens = tokens.astype(np.int64)
        if np.any(tokens < 0) or np.any(tokens >= inner.VOCAB):
            raise ValueError(f"tokens out of range [0, {inner.VOCAB})")
        rows = []
        guard = (self._TP_EXEC_LOCK if self._tp
                 else contextlib.nullcontext())
        with guard:
            for row in tokens:
                caches = inner._fresh_cache()
                logits = None
                # one compiled step per token, fresh cache per row: the
                # same executable (and therefore the same bits) as serving
                # the row through the sequence API in one start+end request
                for pos, tok in enumerate(row.tolist()):
                    logits, caches = inner._step_fn(
                        inner._params, caches, int(tok), pos)
                rows.append(
                    np.asarray(logits, dtype=np.float32).reshape(-1))
        logits_np = np.stack(rows).astype(np.float32)
        return {
            "LOGITS": logits_np,
            "NEXT_TOKEN": logits_np.argmax(axis=1).astype(
                np.int32).reshape(-1, 1),
        }
