"""Model abstraction for the in-process server backend."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterable, List, Optional, Sequence

import numpy as np


@dataclasses.dataclass
class TensorSpec:
    """Metadata for one model input or output (KServe v2 TensorMetadata)."""

    name: str
    datatype: str
    shape: List[int]  # -1 for dynamic dims
    optional: bool = False  # model tolerates this input being absent

    def metadata(self) -> Dict[str, Any]:
        return {"name": self.name, "datatype": self.datatype, "shape": self.shape}

    def matches(self, shape: Sequence[int]) -> bool:
        if len(shape) != len(self.shape):
            return False
        return all(s == d or d == -1 for s, d in zip(shape, self.shape))


class Model:
    """Base class for server-side models.

    ``execute`` receives host ndarrays plus the request parameter bag and
    returns output ndarrays. Decoupled models override ``execute_decoupled``
    to yield multiple responses per request.
    """

    name: str = "model"
    platform: str = "jax"
    versions: List[str] = ["1"]
    max_batch_size: int = 0
    decoupled: bool = False
    stateful: bool = False

    def __init__(self):
        self._ready = True
        # load-time config override (reference: LoadModel config param,
        # http_client.cc:1496-1540) — merged over config() output
        self.config_override: Dict[str, Any] = {}

    # -- registry-facing ---------------------------------------------------
    @property
    def ready(self) -> bool:
        return self._ready

    def load(self) -> None:
        self._ready = True

    def unload(self) -> None:
        self._ready = False

    def inputs(self) -> List[TensorSpec]:
        raise NotImplementedError

    def outputs(self) -> List[TensorSpec]:
        raise NotImplementedError

    def metadata(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "versions": self.versions,
            "platform": self.platform,
            "inputs": [t.metadata() for t in self.inputs()],
            "outputs": [t.metadata() for t in self.outputs()],
        }

    def config(self) -> Dict[str, Any]:
        cfg = {
            "name": self.name,
            "platform": self.platform,
            "backend": "jax",
            "max_batch_size": self.max_batch_size,
            "input": [
                {"name": t.name, "data_type": "TYPE_" + t.datatype, "dims": t.shape}
                for t in self.inputs()
            ],
            "output": [
                {"name": t.name, "data_type": "TYPE_" + t.datatype, "dims": t.shape}
                for t in self.outputs()
            ],
            "model_transaction_policy": {"decoupled": self.decoupled},
        }
        cfg.update(self.config_override)
        return cfg

    def labels(self) -> Optional[List[str]]:
        """Classification labels (for the classification extension); None if n/a."""
        return None

    def effective_max_batch_size(self) -> int:
        """max_batch_size honoring any load-time config override — the value
        behavior must use (config() reports the same one)."""
        return int(self.config_override.get("max_batch_size", self.max_batch_size))

    # -- execution ---------------------------------------------------------
    def execute(
        self, inputs: Dict[str, np.ndarray], parameters: Dict[str, Any]
    ) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def execute_decoupled(
        self, inputs: Dict[str, np.ndarray], parameters: Dict[str, Any]
    ) -> Iterable[Dict[str, np.ndarray]]:
        """Yield one response dict per emitted message (decoupled models)."""
        yield self.execute(inputs, parameters)
