"""Long-context encoder: ring attention served behind the v2 protocol.

Demonstrates the long-context serving path end-to-end: the request's
sequence is sharded over the device mesh, self-attention runs as ring
attention (K/V rotating over ICI, online softmax — no [seq, seq] matrix
ever materializes), and the encoded sequence returns through the normal
data plane. On a single device the ring degenerates gracefully (one hop).
"""

from __future__ import annotations

import math
import threading
from typing import Any, Dict, List

import numpy as np

from .base import Model, TensorSpec


class LongContextEncoderModel(Model):
    """``long_context_encoder``: FP32 [seq, dim] -> attended [seq, dim].

    One multi-head self-attention layer with fixed (seeded) projections —
    the fixture contract for exercising context parallelism, not a trained
    model. ``seq`` must divide by the mesh's data-axis size (except in
    flash mode, which handles arbitrary lengths on one device).
    """

    name = "long_context_encoder"
    platform = "jax_ring_attention"

    def __init__(
        self, dim: int = 64, heads: int = 4, seed: int = 0, n_devices: int = 0,
        attention: str = "ring",
    ):
        """``attention``: "ring" (default — O(seq/n²) memory), "ulysses"
        (all-to-all head repartition, fewer collective steps; heads must
        divide the mesh), or "auto" (see parallel/ulysses.py)."""
        super().__init__()
        if attention not in ("ring", "ulysses", "auto", "flash"):
            raise ValueError(
                f"attention must be ring|ulysses|auto|flash, got {attention!r}"
            )
        self._dim = dim
        self._heads = heads
        self._seed = seed
        self._n_devices = n_devices  # 0 = all available
        self._attention = attention
        self._lock = threading.Lock()
        self._built = None

    def inputs(self) -> List[TensorSpec]:
        return [TensorSpec("sequence", "FP32", [-1, self._dim])]

    def outputs(self) -> List[TensorSpec]:
        return [TensorSpec("encoded", "FP32", [-1, self._dim])]

    def _ensure_built(self):
        with self._lock:
            if self._built is not None:
                return self._built
            import jax
            import jax.numpy as jnp
            from jax.sharding import Mesh

            from ..parallel.ring import place_sharded
            from ..parallel.ulysses import sequence_parallel_attention

            available = len(jax.devices())
            n = self._n_devices or available
            if n > available:
                raise ValueError(
                    f"requested {n} devices but only {available} available"
                )
            # the ring runs over a flat (n, 1) data mesh
            mesh = Mesh(
                np.array(jax.devices()[:n]).reshape(n, 1), ("data", "model")
            )
            rng = jax.random.PRNGKey(self._seed)
            kq, kk, kv, ko = jax.random.split(rng, 4)
            scale = self._dim**-0.5
            wq = jax.random.normal(kq, (self._dim, self._dim), jnp.float32) * scale
            wk = jax.random.normal(kk, (self._dim, self._dim), jnp.float32) * scale
            wv = jax.random.normal(kv, (self._dim, self._dim), jnp.float32) * scale
            wo = jax.random.normal(ko, (self._dim, self._dim), jnp.float32) * scale

            heads = self._heads
            head_dim = self._dim // heads

            attention_mode = self._attention

            @jax.jit  # one compile per sequence length, then cached
            def encode(xb):  # [1, seq, dim] device array
                seq = xb.shape[1]

                def project(w):
                    return (xb @ w).reshape(1, seq, heads, head_dim)

                if attention_mode == "flash":
                    # single-device blocked kernel (Pallas); no mesh hop.
                    # arbitrary lengths: the kernel pads + masks internally
                    from ..ops.flash_attention import flash_attention

                    out = flash_attention(
                        project(wq), project(wk), project(wv),
                    )
                else:
                    out = sequence_parallel_attention(
                        project(wq), project(wk), project(wv), mesh,
                        axis="data", mode=attention_mode,
                    )
                return (out.reshape(1, seq, self._dim) @ wo)[0]

            def run(x):  # [seq, dim] host array
                xb = jnp.asarray(x, jnp.float32)[None]
                if attention_mode != "flash":
                    # the mesh schemes want the sequence sharded; flash is
                    # single-device — placing it on the mesh would just make
                    # XLA all-gather it back per request
                    xb = place_sharded(xb, mesh)
                return encode(xb)

            self._built = (mesh, run)
            return self._built

    def execute(self, inputs: Dict[str, np.ndarray], parameters: Dict[str, Any]):
        mesh, encode = self._ensure_built()
        x = np.asarray(inputs["sequence"], dtype=np.float32)
        n = mesh.shape["data"]
        # flash is single-device (pads + masks internally); only the mesh
        # schemes shard the sequence and need the divisibility
        if self._attention != "flash" and x.shape[0] % n != 0:
            raise ValueError(
                f"sequence length {x.shape[0]} must divide by the mesh's "
                f"data-axis size {n}"
            )
        return {"encoded": encode(x)}
