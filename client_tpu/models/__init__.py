"""JAX model zoo backing the in-process server and examples.

The reference client ships no models — it tests against fixture models hosted
by a real tritonserver (``simple``, ``simple_identity``,
``custom_identity_int32``, ``simple_sequence``, ``repeat_int32``,
``densenet_onnx``; see SURVEY.md §2.4). Here those fixture contracts are
implemented as jitted JAX programs so the framework is self-contained on a
TPU VM: the same wire contracts, but the compute runs on XLA.
"""

from .base import Model, TensorSpec
from .chain import (
    ChainCore,
    ChainEmbedModel,
    ChainFusedModel,
    ChainRerankModel,
    ChainTokenizeModel,
)
from .decoder_batched import BatchedDecoderModel
from .decoder_prefill import PrefillDecoderModel
from .disagg import DisaggPrefillModel, KvDecodeModel
from .ensemble import EnsembleModel, EnsembleStep, build_image_ensemble
from .generate import TinyGenerateModel
from .simple import (
    AddSubModel,
    IdentityModel,
    RepeatModel,
    SequenceAccumulatorModel,
    StringAddSubModel,
    default_model_zoo,
)

__all__ = [
    "AddSubModel",
    "BatchedDecoderModel",
    "ChainCore",
    "ChainEmbedModel",
    "ChainFusedModel",
    "ChainRerankModel",
    "ChainTokenizeModel",
    "DisaggPrefillModel",
    "EnsembleModel",
    "EnsembleStep",
    "IdentityModel",
    "KvDecodeModel",
    "Model",
    "PrefillDecoderModel",
    "RepeatModel",
    "SequenceAccumulatorModel",
    "StringAddSubModel",
    "TensorSpec",
    "TinyGenerateModel",
    "build_image_ensemble",
    "default_model_zoo",
]
