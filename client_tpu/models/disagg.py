"""Disaggregated prefill/decode model pair over the decoder_lm weights.

Production LLM fleets split compute-bound prefill from memory-bound
decode onto differently-provisioned replicas (Hermes, arXiv:2409.04249).
The client-side orchestration (``client_tpu.disagg``) needs server
fixtures for both halves of that split, sharing weights (and the single
compiled decode step) with the zoo's ``decoder_lm``/``tiny_lm_generate``
so the disaggregated token stream is assertable BIT-EXACT against
monolithic generation:

- ``decoder_lm_disagg_prefill`` — stateless prefill: runs the prompt
  through a fresh KV cache and RETURNS the cache as a tensor (plus the
  first greedy token and the fill position). Pure function of the
  prompt, which is what makes re-prefill recovery idempotent by
  construction: re-running it over prompt + already-emitted tokens
  reproduces the exact KV state the lost decode replica held.
- ``decoder_lm_kv_decode`` — decoupled decode-from-handed-off-KV:
  accepts the exported KV tensor, the fill position and the first
  pending token, and streams greedy tokens exactly like
  ``tiny_lm_generate``'s per-token path (one response per token, INDEX
  offset by ``START_INDEX`` so a resumed stream numbers tokens
  globally).

The KV rides the wire as FP32 (``[LAYERS*2, HEADS, MAX_LEN, Dh]``; row
``2l`` is layer ``l``'s K, row ``2l+1`` its V). bf16 → fp32 widening is
exact and narrowing an exactly-representable value back is exact, so
the round-trip is bit-preserving while keeping the handoff buffer a
plain numpy dtype the client can digest (blake2b) and stage through the
shared-memory arena without bf16 special-casing.

Wire contracts:
  decoder_lm_disagg_prefill (unary):
    inputs:  TOKENS     INT32[1, -1]  prompt token ids
    outputs: KV         FP32[L*2, H, M, Dh]  the filled cache
             NEXT_TOKEN INT32[1, 1]   greedy argmax after the last token
             POS        INT32[1, 1]   tokens consumed (cache fill level)
  decoder_lm_kv_decode (decoupled — use streaming inference):
    inputs:  KV          FP32[L*2, H, M, Dh]  handed-off cache
             POS         INT32[1]     cache fill level
             FIRST_TOKEN INT32[1]     first pending (un-emitted) token
             MAX_TOKENS  INT32[1]     tokens to emit (optional, default 16)
             END_ID      INT32[1]     stop token id (optional; stops AFTER
                                      emitting it)
             START_INDEX INT32[1]     INDEX of the first emitted token
                                      (optional, default 0 — resumed
                                      streams pass tokens-already-emitted)
    outputs: NEXT_TOKEN  INT32[1, 1]  one generated token per response
             INDEX       INT32[1, 1]  global position of that token
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List

import numpy as np

from .base import Model, TensorSpec
from .decoder import TinyDecoderModel


def _kv_shape(dec: TinyDecoderModel) -> List[int]:
    return [dec.LAYERS * 2, dec.HEADS, dec.MAX_LEN, dec.D_MODEL // dec.HEADS]


class DisaggPrefillModel(Model):
    """``decoder_lm_disagg_prefill``: stateless prompt prefill that
    exports the KV cache for handoff to a decode-role replica."""

    name = "decoder_lm_disagg_prefill"
    platform = "jax"
    max_batch_size = 0

    def __init__(self, seed: int = 0, decoder: TinyDecoderModel = None):
        super().__init__()
        # weight/step sharing by composition (see TinyGenerateModel):
        # bit-exactness across serving styles requires ONE parameter set
        self._decoder = (decoder if decoder is not None
                         else TinyDecoderModel(seed=seed))

    def inputs(self) -> List[TensorSpec]:
        return [TensorSpec("TOKENS", "INT32", [1, -1])]

    def outputs(self) -> List[TensorSpec]:
        return [
            TensorSpec("KV", "FP32", _kv_shape(self._decoder)),
            TensorSpec("NEXT_TOKEN", "INT32", [1, 1]),
            TensorSpec("POS", "INT32", [1, 1]),
        ]

    def execute(self, inputs: Dict[str, np.ndarray],
                parameters: Dict[str, Any]) -> Dict[str, np.ndarray]:
        dec = self._decoder
        dec._ensure_built()
        tokens = np.asarray(inputs["TOKENS"]).reshape(-1).astype(np.int64)
        if tokens.size == 0:
            raise ValueError("empty prompt")
        if np.any(tokens < 0) or np.any(tokens >= dec.VOCAB):
            raise ValueError(f"tokens out of range [0, {dec.VOCAB})")
        if tokens.size >= dec.MAX_LEN:
            raise ValueError(f"prompt longer than max_len {dec.MAX_LEN}")

        # same compiled step the monolithic paths use — nothing new
        # compiles, and the produced cache is bit-identical to the state
        # tiny_lm_generate would hold after the same token sequence
        caches, pos = dec._fresh_cache(), 0
        logits = None
        for t in tokens:
            logits, caches = dec._step_fn(dec._params, caches, int(t), pos)
            pos += 1

        # [L*2, H, M, Dh] fp32: exact widening of the bf16 cache
        kv = np.stack(
            [np.asarray(c[half], dtype=np.float32)
             for c in caches for half in ("k", "v")])
        logits_np = np.asarray(logits, dtype=np.float32)
        return {
            "KV": kv,
            "NEXT_TOKEN": np.array([[int(logits_np.argmax())]],
                                   dtype=np.int32),
            "POS": np.array([[pos]], dtype=np.int32),
        }


class KvDecodeModel(Model):
    """``decoder_lm_kv_decode``: decoupled greedy decode resuming from a
    handed-off KV cache (the decode half of the disaggregated split)."""

    name = "decoder_lm_kv_decode"
    platform = "jax"
    max_batch_size = 0
    decoupled = True

    DEFAULT_MAX_TOKENS = 16

    def __init__(self, seed: int = 0, decoder: TinyDecoderModel = None):
        super().__init__()
        self._decoder = (decoder if decoder is not None
                         else TinyDecoderModel(seed=seed))

    def inputs(self) -> List[TensorSpec]:
        return [
            TensorSpec("KV", "FP32", _kv_shape(self._decoder)),
            TensorSpec("POS", "INT32", [1]),
            TensorSpec("FIRST_TOKEN", "INT32", [1]),
            TensorSpec("MAX_TOKENS", "INT32", [1], optional=True),
            TensorSpec("END_ID", "INT32", [1], optional=True),
            TensorSpec("START_INDEX", "INT32", [1], optional=True),
        ]

    def outputs(self) -> List[TensorSpec]:
        return [
            TensorSpec("NEXT_TOKEN", "INT32", [1, 1]),
            TensorSpec("INDEX", "INT32", [1, 1]),
        ]

    def execute(self, inputs, parameters):
        raise ValueError(
            "decoder_lm_kv_decode is a decoupled model; use streaming "
            "inference")

    def execute_decoupled(
        self, inputs: Dict[str, np.ndarray], parameters: Dict[str, Any]
    ) -> Iterable[Dict[str, np.ndarray]]:
        import jax.numpy as jnp

        dec = self._decoder
        dec._ensure_built()
        L, H, M = dec.LAYERS, dec.HEADS, dec.MAX_LEN
        Dh = dec.D_MODEL // H

        kv = np.asarray(inputs["KV"], dtype=np.float32)
        if kv.shape != (L * 2, H, M, Dh):
            raise ValueError(
                f"KV shape {kv.shape} != expected {(L * 2, H, M, Dh)}")
        pos = int(np.asarray(inputs["POS"]).reshape(-1)[0])
        if not 0 < pos <= M:
            raise ValueError(f"POS out of range (0, {M}]")
        next_token = int(np.asarray(inputs["FIRST_TOKEN"]).reshape(-1)[0])
        if not 0 <= next_token < dec.VOCAB:
            raise ValueError(f"FIRST_TOKEN out of range [0, {dec.VOCAB})")
        budget = int(
            np.asarray(inputs.get("MAX_TOKENS", self.DEFAULT_MAX_TOKENS))
            .reshape(-1)[0])
        if budget < 1:
            raise ValueError("MAX_TOKENS must be >= 1")
        end_id = None
        if "END_ID" in inputs:
            end_id = int(np.asarray(inputs["END_ID"]).reshape(-1)[0])
        start_index = int(
            np.asarray(inputs.get("START_INDEX", 0)).reshape(-1)[0])
        if start_index < 0:
            raise ValueError("START_INDEX must be >= 0")

        # narrow back to the bf16 the cache was exported from (exact:
        # every value is bf16-representable) — the step function then
        # sees bit-identical state to the monolithic decode loop
        caches = [
            {"k": jnp.asarray(kv[2 * l], jnp.bfloat16),
             "v": jnp.asarray(kv[2 * l + 1], jnp.bfloat16)}
            for l in range(L)
        ]

        def response(token_id: int, index: int):
            return {
                "NEXT_TOKEN": np.array([[token_id]], dtype=np.int32),
                "INDEX": np.array([[index]], dtype=np.int32),
            }

        # mirrors tiny_lm_generate's per-token path exactly (budget
        # check, END_ID emitted then stop, one step per emitted token)
        emitted = 0
        while emitted < budget:
            yield response(next_token, start_index + emitted)
            emitted += 1
            if emitted >= budget or (end_id is not None
                                     and next_token == end_id):
                return
            if pos >= M:
                return  # static cache exhausted
            logits, caches = dec._step_fn(
                dec._params, caches, next_token, pos)
            pos += 1
            next_token = int(np.asarray(logits).argmax())
