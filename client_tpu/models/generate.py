"""Decoupled autoregressive generation — the LLM serving pattern.

The reference's decoupled transaction policy (repeat_int32 fixture,
SURVEY §2.4 "decoupled/repeat models"; model_transaction_policy in
grpc_service.proto) exists so one request can stream many responses.
Production LLM serving on Triton (the TensorRT-LLM / vLLM backends) is
exactly this shape: the client sends one request carrying the prompt and
``max_tokens`` and receives one streamed response per generated token.
``tiny_lm_generate`` is that contract implemented tpu-first, sharing
weights with the stateful ``decoder_lm`` fixture so greedy generation is
bit-exact across both serving styles (the cross-check the tests pin).

TPU-first choices:
- one compiled decode step (static-shape KV cache, position-based mask —
  see decoder.py) serves prefill AND every generated token: no
  shape-polymorphic retraces, ever;
- multi-token decoding runs INSIDE XLA via ``lax.scan`` when the request
  sets the ``chunk`` parameter > 1: the greedy argmax→feed-back loop is a
  scan carry, so K tokens cost one device dispatch instead of K (the
  dispatch-bound regime on a tunneled chip is exactly where this wins);
  chunk=1 (the default) dispatches per token, which is what a
  streaming-latency harness should measure;
- greedy argmax happens on-device in int32 — the host only ever sees the
  emitted token ids, one int per token.

Wire contract (decoupled — use streaming inference):
  inputs:  TOKENS     INT32[1, -1]  prompt token ids
           MAX_TOKENS INT32[1]      max tokens to generate (optional,
                                    default 16, clamped to cache room)
           END_ID     INT32[1]      stop token id (optional; generation
                                    stops AFTER emitting it)
  outputs: NEXT_TOKEN INT32[1, 1]   one generated token per response
           INDEX      INT32[1, 1]   0-based position of that token
  request parameters: "chunk": int — tokens per device dispatch (default 1)
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Iterable, List

import numpy as np

from .base import Model, TensorSpec
from .decoder import TinyDecoderModel


class TinyGenerateModel(Model):
    """``tiny_lm_generate``: decoupled streaming generation over the
    decoder_lm transformer (same seed → identical weights)."""

    name = "tiny_lm_generate"
    platform = "jax"
    max_batch_size = 0
    decoupled = True

    DEFAULT_MAX_TOKENS = 16

    def __init__(self, seed: int = 0, decoder: TinyDecoderModel = None):
        super().__init__()
        # weight/step sharing by composition: generation must agree with the
        # sequence-API decoder token-for-token. Pass the zoo's decoder_lm
        # instance to share its weights and compiled step (params/step are
        # read-only at serving time; only per-request cache state is local)
        self._decoder = decoder if decoder is not None else TinyDecoderModel(seed=seed)
        self._lock = threading.Lock()
        self._chunk_fns: Dict[int, Any] = {}  # scan length K -> jitted fn

    def inputs(self) -> List[TensorSpec]:
        return [
            TensorSpec("TOKENS", "INT32", [1, -1]),
            TensorSpec("MAX_TOKENS", "INT32", [1], optional=True),
            TensorSpec("END_ID", "INT32", [1], optional=True),
        ]

    def outputs(self) -> List[TensorSpec]:
        return [
            TensorSpec("NEXT_TOKEN", "INT32", [1, 1]),
            TensorSpec("INDEX", "INT32", [1, 1]),
        ]

    # -- compiled pieces -----------------------------------------------------
    def _ensure_built(self):
        self._decoder._ensure_built()

    def _chunk_fn(self, k: int):
        """Jitted K-token greedy decode: the argmax→feed-back loop as a
        ``lax.scan`` carry, one device dispatch for K tokens."""
        with self._lock:
            fn = self._chunk_fns.get(k)
            if fn is not None:
                return fn

        import jax
        import jax.numpy as jnp
        from jax import lax

        step = self._decoder._step_fn

        def decode_k(params, caches, token, pos):
            # int32 up front: the scan carry pytree must keep identical
            # dtypes across iterations (weak-typed host ints would not)
            token = jnp.asarray(token, jnp.int32)
            pos = jnp.asarray(pos, jnp.int32)

            def body(carry, _):
                caches, token, pos = carry
                logits, caches = step(params, caches, token, pos)
                nxt = jnp.argmax(logits).astype(jnp.int32)
                return (caches, nxt, pos + jnp.int32(1)), nxt

            (caches, _, _), toks = lax.scan(
                body, (caches, token, pos), None, length=k)
            return toks, caches

        fn = jax.jit(decode_k)
        with self._lock:
            self._chunk_fns.setdefault(k, fn)
        return self._chunk_fns[k]

    # -- serving -------------------------------------------------------------
    def execute(self, inputs, parameters):
        raise ValueError(
            "tiny_lm_generate is a decoupled model; use streaming inference")

    def execute_decoupled(
        self, inputs: Dict[str, np.ndarray], parameters: Dict[str, Any]
    ) -> Iterable[Dict[str, np.ndarray]]:
        self._ensure_built()
        dec = self._decoder
        max_len = dec.MAX_LEN

        tokens = np.asarray(inputs["TOKENS"]).reshape(-1).astype(np.int64)
        if tokens.size == 0:
            raise ValueError("empty prompt")
        if np.any(tokens < 0) or np.any(tokens >= dec.VOCAB):
            raise ValueError(f"tokens out of range [0, {dec.VOCAB})")
        if tokens.size >= max_len:
            raise ValueError(f"prompt longer than max_len {max_len}")

        max_tokens = int(
            np.asarray(inputs.get("MAX_TOKENS", self.DEFAULT_MAX_TOKENS))
            .reshape(-1)[0])
        if max_tokens < 1:
            raise ValueError("MAX_TOKENS must be >= 1")
        end_id = None
        if "END_ID" in inputs:
            end_id = int(np.asarray(inputs["END_ID"]).reshape(-1)[0])
        chunk = int(parameters.get("chunk", 1))
        if chunk < 1:
            raise ValueError("chunk parameter must be >= 1")

        # room left in the static cache bounds generation length
        budget = min(max_tokens, max_len - int(tokens.size))

        # prefill: the single compiled step over the prompt (same executable
        # the decode loop uses — nothing new compiles per prompt length)
        caches, pos = dec._fresh_cache(), 0
        logits = None
        for t in tokens:
            logits, caches = dec._step_fn(dec._params, caches, int(t), pos)
            pos += 1

        def response(token_id: int, index: int):
            return {
                "NEXT_TOKEN": np.array([[token_id]], dtype=np.int32),
                "INDEX": np.array([[index]], dtype=np.int32),
            }

        emitted = 0
        next_token = int(np.asarray(logits).argmax())
        if chunk == 1:
            # per-token dispatch: one streamed response per device step —
            # honest TTFT/inter-token latency for a perf harness
            while emitted < budget:
                yield response(next_token, emitted)
                emitted += 1
                if emitted >= budget or (end_id is not None
                                         and next_token == end_id):
                    return
                logits, caches = dec._step_fn(
                    dec._params, caches, next_token, pos)
                pos += 1
                next_token = int(np.asarray(logits).argmax())
            return

        # chunked: first token came from prefill; subsequent tokens arrive
        # K at a time from one scan dispatch and stream out burst-wise
        yield response(next_token, emitted)
        emitted += 1
        if end_id is not None and next_token == end_id:
            return
        while emitted < budget:
            k = min(chunk, budget - emitted, max_len - pos)
            if k <= 0:
                return
            toks, caches = self._chunk_fn(k)(
                dec._params, caches, next_token, pos)
            pos += k
            toks = np.asarray(toks).reshape(-1)
            for t in toks:
                yield response(int(t), emitted)
                emitted += 1
                if end_id is not None and int(t) == end_id:
                    return
            next_token = int(toks[-1])
