"""Autoregressive decoder with a KV cache behind the v2 sequence API.

The reference's sequence extension (sequence_id/start/end request
parameters — SURVEY §2.4 sequence examples; simple_sequence is the
accumulator fixture) exists precisely for stateful models. This is the
real thing: a transformer decoder whose per-sequence KV cache lives in
server-side sequence state, exercised one token per request the way an
LLM serving loop drives it.

TPU-first choices:
- the KV cache is STATIC-SHAPE ([max_len, ...] preallocated,
  ``lax.dynamic_update_slice`` at the current position) so the decode step
  compiles ONCE and every token reuses the same executable — no
  shape-polymorphic retraces;
- the attention mask is position-based (iota <= pos) rather than
  shape-based, so one compiled step serves every position;
- weights and math are bf16 (MXU-native) with fp32 softmax/logits.

Wire contract (stateful, one token per request after the start request):
  inputs:  TOKENS INT32[1, -1] — full prompt when sequence_start, exactly
           one token otherwise
  outputs: LOGITS FP32[1, vocab] (next-token logits, fp32)
           NEXT_TOKEN INT32[1, 1] (greedy argmax, a convenience)
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List

import numpy as np

from .base import Model, TensorSpec


class TinyDecoderModel(Model):
    """``decoder_lm``: 2-layer pre-norm transformer decoder fixture."""

    name = "decoder_lm"
    platform = "jax"
    max_batch_size = 0
    stateful = True

    VOCAB = 256
    D_MODEL = 128
    HEADS = 4
    LAYERS = 2
    MAX_LEN = 128

    def __init__(self, seed: int = 0, attention_impl: str = "einsum"):
        """``attention_impl``: "einsum" (dense, default) or "pallas" (the
        ops/decode_attention.py flash-decoding kernel — same math, K/V
        blocks streamed through VMEM; interpret mode off-TPU)."""
        if attention_impl not in ("einsum", "pallas"):
            raise ValueError(f"unknown attention_impl {attention_impl!r}")
        super().__init__()
        self._seed = seed
        self._attention_impl = attention_impl
        self._lock = threading.Lock()
        self._params = None
        self._step_fn = None
        self._sequences: Dict[Any, Dict[str, Any]] = {}
        # per-sequence serialization: concurrent requests on one sequence_id
        # must not interleave read-compute-write (lost KV updates otherwise;
        # the reference's sequence batcher serializes per CORRID the same way)
        self._seq_locks: Dict[Any, threading.Lock] = {}

    def inputs(self) -> List[TensorSpec]:
        return [TensorSpec("TOKENS", "INT32", [1, -1])]

    def outputs(self) -> List[TensorSpec]:
        return [
            TensorSpec("LOGITS", "FP32", [1, self.VOCAB]),
            TensorSpec("NEXT_TOKEN", "INT32", [1, 1]),
        ]

    # -- model ---------------------------------------------------------------
    def _build(self):
        import jax
        import jax.numpy as jnp
        from jax import lax

        D, H, L, V, M = (self.D_MODEL, self.HEADS, self.LAYERS, self.VOCAB,
                         self.MAX_LEN)
        Dh = D // H
        rng = np.random.default_rng(self._seed)

        def w(*shape, scale=None):
            scale = scale if scale is not None else (shape[0] ** -0.5)
            return jnp.asarray(
                rng.standard_normal(shape).astype(np.float32) * scale,
                dtype=jnp.bfloat16)

        params = {
            "embed": w(V, D, scale=0.02),
            "pos": w(M, D, scale=0.02),
            "layers": [
                {
                    "qkv": w(D, 3 * D),
                    "proj": w(D, D),
                    "mlp_in": w(D, 4 * D),
                    "mlp_out": w(4 * D, D),
                }
                for _ in range(L)
            ],
            "unembed": w(D, V, scale=0.02),
        }

        def norm(x):
            x32 = x.astype(jnp.float32)
            mu = jnp.mean(x32, axis=-1, keepdims=True)
            var = jnp.var(x32, axis=-1, keepdims=True)
            return ((x32 - mu) * lax.rsqrt(var + 1e-5)).astype(x.dtype)

        def step(params, caches, token, pos):
            """One decode step. caches: [L] dicts of k/v [H, M, Dh]."""
            x = params["embed"][token] + params["pos"][pos]  # [D]
            new_caches = []
            for layer, cache in zip(params["layers"], caches):
                h = norm(x)
                qkv = h @ layer["qkv"]  # [3D]
                q, k_new, v_new = jnp.split(qkv, 3)
                q = q.reshape(H, Dh)
                k_new = k_new.reshape(H, 1, Dh)
                v_new = v_new.reshape(H, 1, Dh)
                k = lax.dynamic_update_slice(cache["k"], k_new, (0, pos, 0))
                v = lax.dynamic_update_slice(cache["v"], v_new, (0, pos, 0))
                new_caches.append({"k": k, "v": v})
                if self._attention_impl == "pallas":
                    from ..ops.decode_attention import decode_attention

                    attn = decode_attention(
                        q[None], k[None], v[None],
                        jnp.asarray(pos, jnp.int32).reshape(1),
                    )[0]  # [H, Dh], bf16 (kernel accumulates fp32)
                    x = x + (attn.reshape(D) @ layer["proj"])
                else:
                    # position-based mask: only slots <= pos attend
                    scores = jnp.einsum(
                        "hd,hmd->hm", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * (Dh ** -0.5)
                    mask = jnp.arange(M) <= pos
                    scores = jnp.where(mask[None, :], scores, -jnp.inf)
                    probs = jax.nn.softmax(scores, axis=-1)
                    attn = jnp.einsum(
                        "hm,hmd->hd", probs, v.astype(jnp.float32))
                    x = x + (attn.reshape(D).astype(jnp.bfloat16)
                             @ layer["proj"])
                h2 = norm(x)
                x = x + jax.nn.gelu(h2 @ layer["mlp_in"]) @ layer["mlp_out"]
            logits = (norm(x) @ params["unembed"]).astype(jnp.float32)
            return logits, new_caches

        self._params = params
        self._step_fn = jax.jit(step)

    def _ensure_built(self):
        with self._lock:
            if self._step_fn is None:
                self._build()

    def _fresh_cache(self):
        import jax.numpy as jnp

        Dh = self.D_MODEL // self.HEADS
        return [
            {
                "k": jnp.zeros((self.HEADS, self.MAX_LEN, Dh), jnp.bfloat16),
                "v": jnp.zeros((self.HEADS, self.MAX_LEN, Dh), jnp.bfloat16),
            }
            for _ in range(self.LAYERS)
        ]

    # -- serving -------------------------------------------------------------
    def execute(self, inputs: Dict[str, np.ndarray], parameters: Dict[str, Any]):
        self._ensure_built()
        seq_id = parameters.get("sequence_id", 0)
        start = parameters.get("sequence_start", False)
        end = parameters.get("sequence_end", False)
        if not seq_id:
            raise ValueError("decoder_lm requires a sequence_id")

        tokens = np.asarray(inputs["TOKENS"]).reshape(-1).astype(np.int64)
        if np.any(tokens < 0) or np.any(tokens >= self.VOCAB):
            raise ValueError(f"tokens out of range [0, {self.VOCAB})")

        with self._lock:
            seq_lock = self._seq_locks.setdefault(seq_id, threading.Lock())

        # the whole read-compute-write is serialized PER SEQUENCE (other
        # sequences decode concurrently); without this, two requests on one
        # sequence_id both read pos=P and the later writer silently drops
        # the earlier token's KV update
        with seq_lock:
            with self._lock:
                if start:
                    state = {"caches": self._fresh_cache(), "pos": 0}
                else:
                    state = self._sequences.get(seq_id)
                    if state is None:
                        raise ValueError(
                            f"sequence {seq_id} has no live state "
                            "(missing sequence_start?)")
                    if len(tokens) != 1:
                        raise ValueError(
                            "continuation requests carry exactly one token")
                if state["pos"] + len(tokens) > self.MAX_LEN:
                    raise ValueError(
                        f"sequence longer than max_len {self.MAX_LEN}")

            # the compiled step runs one token at a time — same executable
            # for prefill and decode (static shapes; cache carries history)
            caches, pos = state["caches"], state["pos"]
            logits = None
            for t in tokens:
                logits, caches = self._step_fn(
                    self._params, caches, int(t), pos)
                pos += 1

            with self._lock:
                if end:
                    self._sequences.pop(seq_id, None)
                    self._seq_locks.pop(seq_id, None)
                else:
                    self._sequences[seq_id] = {"caches": caches, "pos": pos}

        logits_np = np.asarray(logits, dtype=np.float32).reshape(1, self.VOCAB)
        return {
            "LOGITS": logits_np,
            "NEXT_TOKEN": np.array([[int(logits_np.argmax())]], dtype=np.int32),
        }

    def live_sequences(self) -> int:
        with self._lock:
            return len(self._sequences)
