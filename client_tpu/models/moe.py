"""Mixture-of-experts model served behind the v2 protocol.

The expert-parallel twin of ``long_context.py`` (which serves the
sequence-parallel families): a top-1 routed MoE FFN whose expert weights
shard over the device mesh, with tokens dispatched over ``all_to_all``
(``parallel/moe.py``). Fixture contract, seeded weights — exercises ep in
serving, not a trained model.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List

import numpy as np

from .base import Model, TensorSpec


class MoEFFNModel(Model):
    """``moe_ffn``: FP32 [tokens, dim] -> routed expert outputs, same shape.

    ``tokens`` must divide by the mesh axis size (the dispatch shards the
    token dim); experts divide the axis by construction.
    """

    name = "moe_ffn"
    platform = "jax_moe_ep"

    def __init__(
        self, dim: int = 32, hidden: int = 64, experts_per_device: int = 2,
        seed: int = 0, n_devices: int = 0,
    ):
        super().__init__()
        self._dim = dim
        self._hidden = hidden
        self._experts_per_device = experts_per_device
        self._seed = seed
        self._n_devices = n_devices
        self._lock = threading.Lock()
        self._built = None

    def inputs(self) -> List[TensorSpec]:
        return [TensorSpec("tokens", "FP32", [-1, self._dim])]

    def outputs(self) -> List[TensorSpec]:
        return [TensorSpec("routed", "FP32", [-1, self._dim])]

    def _ensure_built(self):
        with self._lock:
            if self._built is not None:
                return self._built
            import jax
            import jax.numpy as jnp
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

            from ..parallel.moe import moe_ffn

            available = len(jax.devices())
            n = self._n_devices or available
            if n > available:
                raise ValueError(
                    f"requested {n} devices but only {available} available"
                )
            mesh = Mesh(
                np.array(jax.devices()[:n]).reshape(1, n), ("data", "model")
            )
            n_experts = self._experts_per_device * n
            rng = jax.random.PRNGKey(self._seed)
            kg, k1, k2 = jax.random.split(rng, 3)
            scale = self._dim**-0.5
            gate_w = jax.random.normal(
                kg, (self._dim, n_experts), jnp.float32) * scale
            w1 = jax.device_put(
                jax.random.normal(
                    k1, (n_experts, self._dim, self._hidden), jnp.float32
                ) * scale,
                NamedSharding(mesh, P("model", None, None)),
            )
            w2 = jax.device_put(
                jax.random.normal(
                    k2, (n_experts, self._hidden, self._dim), jnp.float32
                ) * scale,
                NamedSharding(mesh, P("model", None, None)),
            )

            def run(x):  # [tokens, dim] host array
                tokens = jnp.asarray(x, jnp.float32)
                sharded = jax.device_put(
                    tokens, NamedSharding(mesh, P("model", None))
                )
                return moe_ffn(sharded, gate_w, w1, w2, mesh, axis="model")

            self._built = (mesh, run)
            return self._built

    def execute(self, inputs: Dict[str, np.ndarray], parameters: Dict[str, Any]):
        mesh, run = self._ensure_built()
        x = inputs["tokens"]
        n = mesh.shape["model"]
        if x.shape[0] % n != 0:
            from ..server.core import InferError

            raise InferError(
                f"token count {x.shape[0]} must divide by the mesh axis "
                f"size {n}", 400,
            )
        return {"routed": run(x)}
