"""Tensor-parallel autoregressive decode behind the v2 sequence API.

``decoder_lm`` (models/decoder.py) serves one sequence per device;
``decoder_lm_tp`` is the multi-chip serving story: the SAME decode step —
same weights, same math, same wire contract — executed SPMD over a
``jax.sharding.Mesh`` axis, the way a production LLM too big for one chip
is served. Megatron-style layout, expressed as shardings (XLA/GSPMD
inserts the collectives — no hand-written psum):

- attention is head-sharded: ``wq/wk/wv [D, H, Dh]`` and the per-sequence
  KV caches ``[H, M, Dh]`` are partitioned on the head axis, so cache
  update + softmax + weighted sum are fully local per shard (zero
  attention collectives);
- ``mlp_in [D, 4D]`` is column-parallel (sharded output features) — each
  output element is still a FULL contraction, so no re-association;
- the row-side contractions (attention output projection, ``mlp_out``)
  run replicated on gathered activations: an explicit sharding constraint
  all-gathers the per-shard ``[H, Dh]`` / ``[4D]`` activation vectors
  (tiny next to the caches) and the whole contraction happens on every
  device. This trades Megatron's psum for an all-gather deliberately:
  a psum re-associates the contraction's partial sums, and re-associated
  f32 rounding near an argmax tie changes greedy tokens — the serving
  guarantee here is BIT-equality with the single-device decoder, so
  collectives move data and never split a reduction;
- embeddings/unembed are replicated (tiny for this fixture; a production
  vocab would shard the unembed and all-gather logits).

The KV cache for every live sequence stays device-resident and sharded
for the sequence's whole life — requests only ship one token over the
wire, which is the sequence API's entire point (reference contract:
simple_grpc_sequence_stream_infer_client.py:59-81).

Serving logic (sequence table, per-CORRID locks, validation) is inherited
from TinyDecoderModel unchanged — this class only swaps the compiled step
and cache placement, which is exactly the separation a tpu-first design
wants: parallelism is a compilation/placement concern, not a protocol one.
"""

from __future__ import annotations

from typing import Optional

from .decoder import TinyDecoderModel


class TPDecoderModel(TinyDecoderModel):
    """``decoder_lm_tp``: TinyDecoderModel sharded over a mesh axis."""

    name = "decoder_lm_tp"

    def __init__(self, seed: int = 0, tp: Optional[int] = None, mesh=None,
                 axis: str = "model"):
        """``mesh``+``axis``: serve over an existing mesh's axis (the
        server's multi-chip mesh); ``tp``: build a private 1D mesh over the
        first ``tp`` devices. HEADS (4) must divide by the axis size."""
        super().__init__(seed=seed)
        self._mesh = mesh
        self._axis = axis
        self._tp = tp

    def _ensure_mesh(self):
        import jax
        from jax.sharding import Mesh

        if self._mesh is None:
            import numpy as np

            devices = jax.devices()
            if self._tp:
                tp = self._tp
            else:
                # auto: the largest divisor of HEADS that fits the host —
                # a 3-device host serves tp=2, not a divisibility error
                tp = max(d for d in range(1, self.HEADS + 1)
                         if self.HEADS % d == 0 and d <= len(devices))
            if tp > len(devices):
                raise ValueError(
                    f"tp={tp} but only {len(devices)} devices")
            self._mesh = Mesh(np.array(devices[:tp]), (self._axis,))
        size = self._mesh.shape[self._axis]
        if self.HEADS % size:
            raise ValueError(
                f"HEADS={self.HEADS} not divisible by {self._axis} axis "
                f"size {size}")
        return self._mesh

    @property
    def tp_degree(self) -> int:
        return self._ensure_mesh().shape[self._axis]

    # -- compiled pieces -----------------------------------------------------
    def _build(self):
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import NamedSharding, PartitionSpec as P

        # mesh validation FIRST: if it raises after super()._build() had
        # set _step_fn, _ensure_built would consider the model built and
        # silently serve single-device decode under the tp name
        mesh = self._ensure_mesh()
        super()._build()  # base params + single-device step (same seed)
        ax = self._axis
        D, H, V, M = self.D_MODEL, self.HEADS, self.VOCAB, self.MAX_LEN
        Dh = D // H

        def put(x, spec):
            return jax.device_put(x, NamedSharding(mesh, spec))

        # re-express the fused qkv/proj weights head-major and place them;
        # numerically identical contractions, just indexed per head
        tp_layers = []
        for layer in self._params["layers"]:
            qkv = layer["qkv"]  # [D, 3D]
            tp_layers.append({
                "wq": put(qkv[:, :D].reshape(D, H, Dh), P(None, ax, None)),
                "wk": put(qkv[:, D:2 * D].reshape(D, H, Dh),
                          P(None, ax, None)),
                "wv": put(qkv[:, 2 * D:].reshape(D, H, Dh),
                          P(None, ax, None)),
                "proj": put(layer["proj"].reshape(H, Dh, D), P()),
                "mlp_in": put(layer["mlp_in"], P(None, ax)),
                "mlp_out": put(layer["mlp_out"], P()),
            })
        self._params = {
            "embed": put(self._params["embed"], P()),
            "pos": put(self._params["pos"], P()),
            "layers": tp_layers,
            "unembed": put(self._params["unembed"], P()),
        }
        self._cache_sharding = NamedSharding(mesh, P(ax, None, None))

        def norm(x):
            x32 = x.astype(jnp.float32)
            mu = jnp.mean(x32, axis=-1, keepdims=True)
            var = jnp.var(x32, axis=-1, keepdims=True)
            return ((x32 - mu) * lax.rsqrt(var + 1e-5)).astype(x.dtype)

        def step(params, caches, token, pos):
            x = params["embed"][token] + params["pos"][pos]  # [D] replicated
            new_caches = []
            for layer, cache in zip(params["layers"], caches):
                h = norm(x)
                # head-sharded projections: outputs [H, Dh] partitioned on H
                q = jnp.einsum("d,dhk->hk", h, layer["wq"])
                k_new = jnp.einsum("d,dhk->hk", h, layer["wk"])[:, None, :]
                v_new = jnp.einsum("d,dhk->hk", h, layer["wv"])[:, None, :]
                k = lax.dynamic_update_slice(cache["k"], k_new, (0, pos, 0))
                v = lax.dynamic_update_slice(cache["v"], v_new, (0, pos, 0))
                new_caches.append({"k": k, "v": v})
                # attention fully local per head shard
                scores = jnp.einsum(
                    "hd,hmd->hm", q.astype(jnp.float32),
                    k.astype(jnp.float32)) * (Dh ** -0.5)
                mask = jnp.arange(M) <= pos
                scores = jnp.where(mask[None, :], scores, -jnp.inf)
                probs = jax.nn.softmax(scores, axis=-1)
                attn = jnp.einsum("hm,hmd->hd", probs, v.astype(jnp.float32))
                # all-gather the head-sharded activations, then contract
                # WHOLE on every device (bit-equality; see module doc)
                attn = jax.lax.with_sharding_constraint(
                    attn, NamedSharding(mesh, P()))
                x = x + jnp.einsum(
                    "hk,hkd->d", attn.astype(jnp.bfloat16), layer["proj"])
                h2 = norm(x)
                h_mid = jax.nn.gelu(h2 @ layer["mlp_in"])  # [4D] sharded
                h_mid = jax.lax.with_sharding_constraint(
                    h_mid, NamedSharding(mesh, P()))
                x = x + h_mid @ layer["mlp_out"]
            logits = (norm(x) @ params["unembed"]).astype(jnp.float32)
            return logits, new_caches

        self._step_fn = jax.jit(
            step, out_shardings=(
                NamedSharding(mesh, P()),
                [{"k": self._cache_sharding, "v": self._cache_sharding}
                 for _ in range(self.LAYERS)],
            ))

    def _fresh_cache(self):
        import jax
        import jax.numpy as jnp

        Dh = self.D_MODEL // self.HEADS
        zeros = jnp.zeros((self.HEADS, self.MAX_LEN, Dh), jnp.bfloat16)
        return [
            {
                "k": jax.device_put(zeros, self._cache_sharding),
                "v": jax.device_put(zeros, self._cache_sharding),
            }
            for _ in range(self.LAYERS)
        ]
