"""Ensemble models: a DAG of member models executed server-side.

Parity target: the reference's ensemble examples (ensemble_image_client.*)
rely on tritonserver's ensemble scheduler — a pipeline defined by steps with
input/output tensor maps. Here an ensemble is itself a Model whose execute()
walks the steps through the registry, so clients use it like any other model.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from .base import Model, TensorSpec


class EnsembleStep:
    """One pipeline stage: run ``model_name`` with renamed inputs/outputs.

    ``input_map``: ensemble-tensor-name -> member-model input name.
    ``output_map``: member-model output name -> ensemble-tensor-name.
    """

    def __init__(
        self, model_name: str, input_map: Dict[str, str], output_map: Dict[str, str]
    ):
        self.model_name = model_name
        self.input_map = input_map
        self.output_map = output_map


class EnsembleModel(Model):
    """A sequential ensemble over registered member models."""

    platform = "ensemble"

    def __init__(
        self,
        name: str,
        steps: Sequence[EnsembleStep],
        inputs: Sequence[TensorSpec],
        outputs: Sequence[TensorSpec],
    ):
        super().__init__()
        self.name = name
        self._steps = list(steps)
        self._inputs = list(inputs)
        self._outputs = list(outputs)
        # bound by ServerCore.add_model so steps resolve against the registry
        self._resolver: Optional[Callable[[str], Model]] = None

    def bind(self, resolver: Callable[[str], Model]) -> None:
        self._resolver = resolver

    def inputs(self) -> List[TensorSpec]:
        return list(self._inputs)

    def outputs(self) -> List[TensorSpec]:
        return list(self._outputs)

    def labels(self):
        # classification labels come from the final step's model
        if self._resolver is None or not self._steps:
            return None
        return self._resolver(self._steps[-1].model_name).labels()

    def config(self) -> Dict[str, Any]:
        cfg = super().config()
        cfg["platform"] = "ensemble"
        cfg["ensemble_scheduling"] = {
            "step": [
                {
                    "model_name": s.model_name,
                    "model_version": -1,
                    # Triton's proto orientation: key = member model tensor
                    # name, value = ensemble-scoped tensor name (both maps)
                    "input_map": {m: e for e, m in s.input_map.items()},
                    "output_map": s.output_map,
                }
                for s in self._steps
            ]
        }
        return cfg

    def execute(self, inputs: Dict[str, np.ndarray], parameters: Dict[str, Any]):
        if self._resolver is None:
            raise RuntimeError(
                f"ensemble '{self.name}' is not bound to a model registry"
            )
        # the tensor pool flows ensemble-scoped names through the steps
        pool: Dict[str, Any] = dict(inputs)
        for step in self._steps:
            member = self._resolver(step.model_name)
            member_inputs = {}
            for pool_name, member_name in step.input_map.items():
                if pool_name not in pool:
                    raise ValueError(
                        f"ensemble '{self.name}' step '{step.model_name}': "
                        f"tensor '{pool_name}' not produced by any prior step"
                    )
                member_inputs[member_name] = pool[pool_name]
            member_outputs = member.execute(member_inputs, parameters)
            for member_name, pool_name in step.output_map.items():
                if member_name not in member_outputs:
                    raise ValueError(
                        f"ensemble '{self.name}' step '{step.model_name}': "
                        f"model produced no output '{member_name}'"
                    )
                pool[pool_name] = member_outputs[member_name]
        missing = [spec.name for spec in self._outputs if spec.name not in pool]
        if missing:
            raise ValueError(
                f"ensemble '{self.name}': declared outputs {missing} were not "
                "produced by any step's output_map"
            )
        return {spec.name: pool[spec.name] for spec in self._outputs}


def build_image_ensemble(
    num_classes: int = 1000, width: int = 32, tensor_parallel: int = 1
) -> List[Model]:
    """The ensemble_image pipeline: [preprocess, densenet_onnx, ensemble].

    Register all three; clients send a raw UINT8 HWC "IMAGE" to
    ``ensemble_image`` and get "CLASSIFICATION" (densenet logits) back.
    """
    from .vision import DenseNetModel, ImagePreprocessModel

    preprocess = ImagePreprocessModel()
    densenet = DenseNetModel(
        num_classes=num_classes, width=width, tensor_parallel=tensor_parallel
    )
    ensemble = EnsembleModel(
        "ensemble_image",
        steps=[
            EnsembleStep("preprocess", {"IMAGE": "raw_image"}, {"preprocessed": "stage0"}),
            EnsembleStep("densenet_onnx", {"stage0": "data_0"}, {"fc6_1": "CLASSIFICATION"}),
        ],
        inputs=[TensorSpec("IMAGE", "UINT8", [-1, -1, 3])],
        outputs=[TensorSpec("CLASSIFICATION", "FP32", [num_classes, 1, 1])],
    )
    return [preprocess, densenet, ensemble]
