"""The 3-stage chain fixtures behind ``client_tpu.pipeline``'s proofs.

Four models over ONE shared parameter/step core (:class:`ChainCore`):

- ``chain_tokenize``: RAW INT32[B,L] -> TOKENS INT32[B,L], a fixed
  affine hash into the vocab (``(RAW * 31 + 7) % VOCAB``).
- ``chain_embed``: TOKENS INT32[B,L] -> EMBED FP32[B,L,32], a seeded
  embedding-table gather.
- ``chain_rerank``: EMBED FP32[B,L,32] -> SCORES FP32[B,L], a seeded
  linear projection.
- ``chain_fused``: RAW INT32[B,L] -> SCORES FP32[B,L], the monolithic
  reference running the SAME three compiled step functions end-to-end.

Bit-exactness between a pipeline run of the three stages and one
``chain_fused`` call is BY CONSTRUCTION, not by tolerance: the fused
model composes the very jitted callables the stage models serve (the
disagg.py weight-sharing proof pattern) — it never re-jits a fused
program whose XLA fusion could reassociate the float math.
"""

from __future__ import annotations

import threading
from typing import Dict, List

import numpy as np

from .base import Model, TensorSpec

VOCAB = 997
EMBED_DIM = 32
_SEED = 20260807


class ChainCore:
    """Shared seeded parameters + lazily-jitted step functions for the
    chain fixtures. ONE instance backs all four models so stage-by-stage
    and fused execution run bit-identical compiled steps."""

    def __init__(self, seed: int = _SEED):
        rng = np.random.default_rng(seed)
        self.table = rng.standard_normal(
            (VOCAB, EMBED_DIM)).astype(np.float32)
        self.proj = rng.standard_normal((EMBED_DIM,)).astype(np.float32)
        self.bias = np.float32(rng.standard_normal())
        self._lock = threading.Lock()
        self._fns = None

    def fns(self):
        with self._lock:
            if self._fns is None:
                import jax
                import jax.numpy as jnp

                table = jnp.asarray(self.table)
                proj = jnp.asarray(self.proj)
                bias = jnp.asarray(self.bias)

                @jax.jit
                def tokenize(raw):
                    return (raw * 31 + 7) % VOCAB

                @jax.jit
                def embed(tokens):
                    return table[tokens % VOCAB]

                @jax.jit
                def rerank(embedded):
                    return jnp.einsum("ble,e->bl", embedded, proj) + bias

                self._fns = (tokenize, embed, rerank)
            return self._fns


_CORE: ChainCore = ChainCore()


def chain_core() -> ChainCore:
    """The module-level shared core (models default to it)."""
    return _CORE


class _ChainModel(Model):
    def __init__(self, core: ChainCore = None):
        super().__init__()
        self.core = core or chain_core()


class ChainTokenizeModel(_ChainModel):
    """``chain_tokenize``: RAW INT32[B,L] -> TOKENS INT32[B,L]."""

    name = "chain_tokenize"

    def inputs(self) -> List[TensorSpec]:
        return [TensorSpec("RAW", "INT32", [-1, -1])]

    def outputs(self) -> List[TensorSpec]:
        return [TensorSpec("TOKENS", "INT32", [-1, -1])]

    def execute(self, inputs, parameters) -> Dict[str, np.ndarray]:
        tokenize, _, _ = self.core.fns()
        return {"TOKENS": tokenize(inputs["RAW"])}


class ChainEmbedModel(_ChainModel):
    """``chain_embed``: TOKENS INT32[B,L] -> EMBED FP32[B,L,32]."""

    name = "chain_embed"

    def inputs(self) -> List[TensorSpec]:
        return [TensorSpec("TOKENS", "INT32", [-1, -1])]

    def outputs(self) -> List[TensorSpec]:
        return [TensorSpec("EMBED", "FP32", [-1, -1, EMBED_DIM])]

    def execute(self, inputs, parameters) -> Dict[str, np.ndarray]:
        _, embed, _ = self.core.fns()
        return {"EMBED": embed(inputs["TOKENS"])}


class ChainRerankModel(_ChainModel):
    """``chain_rerank``: EMBED FP32[B,L,32] -> SCORES FP32[B,L]."""

    name = "chain_rerank"

    def inputs(self) -> List[TensorSpec]:
        return [TensorSpec("EMBED", "FP32", [-1, -1, EMBED_DIM])]

    def outputs(self) -> List[TensorSpec]:
        return [TensorSpec("SCORES", "FP32", [-1, -1])]

    def execute(self, inputs, parameters) -> Dict[str, np.ndarray]:
        _, _, rerank = self.core.fns()
        return {"SCORES": rerank(inputs["EMBED"])}


class ChainFusedModel(_ChainModel):
    """``chain_fused``: the monolithic RAW -> SCORES reference, running
    the same compiled steps the three stage models serve."""

    name = "chain_fused"

    def inputs(self) -> List[TensorSpec]:
        return [TensorSpec("RAW", "INT32", [-1, -1])]

    def outputs(self) -> List[TensorSpec]:
        return [TensorSpec("SCORES", "FP32", [-1, -1])]

    def execute(self, inputs, parameters) -> Dict[str, np.ndarray]:
        tokenize, embed, rerank = self.core.fns()
        return {"SCORES": rerank(embed(tokenize(inputs["RAW"])))}
