"""client_tpu — a TPU-native client framework for the KServe v2 inference protocol.

A from-scratch rebuild of the capabilities of the Triton Inference Server
client libraries (triton-inference-server/client), designed TPU-first:

- ``client_tpu.http`` / ``client_tpu.grpc``: sync, callback-async, asyncio and
  bi-directional streaming clients for the KServe v2 protocol (HTTP/REST and
  GRPC), including the full server-management surface.
- ``client_tpu.resilience``: transport-agnostic retry/backoff + circuit
  breaker policies every frontend runs under (``configure_resilience``),
  with idempotency-aware fault classification and GRPC stream
  auto-reconnect; ``client_tpu.testing.chaos`` is the fault-injection
  proxy that proves them end-to-end (docs/resilience.md).
- ``client_tpu.pool``: health-aware multi-endpoint pool over all four
  frontends — active ready-probing + passive outlier ejection, routing
  policies with per-endpoint circuit breakers, shared-deadline failover
  (sequence requests are never silently re-sent), and hedged requests.
- ``client_tpu.admission``: adaptive admission control — an AIMD /
  gradient2-style concurrency limiter over observed latency, priority
  lanes with deadline-aware LIFO shedding (typed ``AdmissionRejected``,
  counted as shed-not-error everywhere), wired through the pool
  (``PoolClient(admission=..., endpoint_limits=...)``) together with the
  ``orca_weighted`` routing policy that feeds smooth-WRR weights from
  the servers' ORCA load reports (docs/admission.md).
- ``client_tpu.batch``: client-side adaptive micro-batching — an opt-in
  coalescing dispatcher (``BatchingClient``/``AioBatchingClient``, or
  ``.coalescing()`` on any frontend/pool) that stacks concurrent
  compatible ``infer()`` calls into one KServe request within an
  arrival-rate-tuned window and scatters result rows back per caller
  (docs/batching.md).
- ``client_tpu.cache``: hot-key serving — client-side singleflight
  (concurrent identical ``infer()`` calls collapse onto one wire
  request) plus a bounded LRU+TTL response cache whose entries are
  zero-copy arena-lease views, with explicit/automatic invalidation and
  typed stale-while-revalidate (``CachingClient``/``AioCachingClient``,
  or ``.caching()`` on any frontend/pool), paired with the pool's
  ``routing="affinity"`` rendezvous session/prefix routing
  (docs/caching.md).
- ``client_tpu.tenancy``: multi-tenant QoS — declared per-tenant
  contracts (``TenantSpec``: WFQ weight, token-bucket rate/burst quota,
  latency SLO, cache byte budget) enforced end to end: every frontend
  and wrapper accepts ``infer(..., tenant=...)``; the admission
  controller drains per-tenant virtual queues weighted-fair and sheds
  over-quota tenants with the typed ``over_quota`` reason and an honest
  ``retry_after_s`` (``SHED`` domain — never retried, never spilled
  cross-cell); the tenant is folded into the shared content key so
  cache/singleflight/batching partition per tenant, with per-tenant
  cache byte budgets; per-tenant SLO burn windows feed telemetry and
  the doctor's ``noisy_neighbor`` anomaly (docs/tenancy.md).
- ``client_tpu.federation``: multi-cell federation —
  ``FederatedClient``/``AioFederatedClient`` over NAMED cells (each an
  existing pool client): locality-first routing with transparent
  spillover when the home cell is saturated (admission sheds become
  spill triggers under a shed-rate hysteresis), down (per-cell circuit
  breakers) or blackholed — under one shared attempt budget, with
  sequences/streams pinned to their established cell (typed
  ``CellSequenceAbandoned``, never a silent cross-cell re-send) — plus
  weighted rollout primitives: shadow mirroring (sampled duplicates
  compared bit-for-bit, never returned, never billed) and canary with
  SLO-burn auto-rollback (typed ``CanaryRolledBack``)
  (docs/federation.md).
- ``client_tpu.observe``: client-side observability — request-phase span
  tracing with sampling and Chrome trace dumps, a Prometheus/JSON metrics
  registry fed by the resilience + pool event streams, and W3C
  ``traceparent`` propagation joined to server-side access records and a
  ``/metrics`` endpoint (docs/observability.md).
- ``client_tpu.flight``: the flight recorder — always-on per-request
  causal timelines assembled from structured events every layer emits
  (retries, breaker flips, routing/affinity decisions, admission
  park/shed, batch join/dispatch, cache hit/collapse, arena leases,
  shard fan-out, stream reconnects), with **tail-based retention**: a
  commit-time verdict keeps errored/shed/SLO-breached/slowest-percentile
  timelines (plus a baseline sample) in a bounded ring and drops the
  fast healthy majority wholesale; exporters, the ``tail_divergence``
  anomaly, and ``doctor --postmortem`` bundles
  (docs/observability.md "Flight recorder & postmortems").
- ``client_tpu.watch``: continuous monitoring — a background
  ``Watchtower`` over the live telemetry with three pillars: a
  crash-safe **black box** (mmap-backed on-disk ring of checksummed
  records the flight recorder and metrics registry drain into, so
  ``doctor --blackbox PATH`` reconstructs retained timelines, metric
  snapshots and alerts after a ``kill -9``; torn tails skipped, never
  raised); **multi-window burn-rate alerting** (fast/slow dual-window
  burn over declared SLOs plus watermark rules on breaker/quarantine/
  shed/arena gauges, typed ``Alert`` edges deduplicated to pluggable
  sinks); and **seeded deterministic changepoint detection** (CUSUM/
  Page-Hinkley over the windowed p99/shed streams, each trip attributed
  via flight ``tail_divergence`` to the endpoint or layer that moved —
  or named a fleet shift) (docs/observability.md "Continuous monitoring
  & black box").
- ``client_tpu.arena``: the pooled shm arena — size-class slab allocator
  over both shared-memory packages with ref-counted leases, LRU watermark
  trimming and per-endpoint cached server registrations; the transparent
  zero-copy fast path behind ``configure_arena``/``shm_arena=`` and
  ``set_data_from_numpy(..., arena=...)`` (docs/tpu_shared_memory.md).
- ``client_tpu.shard``: sharded scatter-gather serving — a
  ``PartitionSpec``-like ``ShardLayout`` maps tensor axes to
  replica-pinned endpoints; ``ShardedClient``/``AioShardedClient`` split
  one logical ``infer()`` into per-shard requests fanned out through the
  pool, staged zero-copy via the arena, and gathered with exactness
  asserts; a lost shard fails the whole request with a typed
  ``ShardFailed`` (docs/sharding.md).
- ``client_tpu.disagg``: disaggregated prefill/decode serving —
  ``DisaggClient``/``AioDisaggClient`` route the prefill infer to a
  ``role="prefill"`` replica and the decode stream to a ``role="decode"``
  one, handing the KV cache off through the shared arena under a
  digest-verified ``KvHandoff`` manifest (mismatch = typed
  ``HandoffCorrupt``); a decode replica dying mid-stream recovers by
  idempotent re-prefill with every token delivered exactly once, and a
  degraded role falls back to monolithic serving behind a typed
  ``RoleFallback`` event (docs/disaggregation.md).
- ``client_tpu.pipeline``: client-side model-DAG pipelines — declared
  ``Pipeline`` graphs of ``Stage``\\ s validated at construction (typed
  ``PipelineConfigError``) and executed client-orchestrated by
  ``PipelineClient``/``AioPipelineClient``; intermediates never
  round-trip the host (shm-arena leases handed off by handle, 0 region
  creates / 0 registration RPCs steady state, lifetime-planned slab
  residency equal to the plan's high-water mark); one admission token +
  one attempt budget per run, a failed stage cancels dependents and
  raises ``StageFailed`` naming the stage (docs/pipelines.md).
- ``client_tpu.utils``: Triton<->numpy dtype mapping with *native* bfloat16
  (via ml_dtypes), BYTES/BF16 wire serialization.
- ``client_tpu.utils.shared_memory``: POSIX system shared memory data plane.
- ``client_tpu.utils.tpu_shared_memory``: the TPU-native zero-copy data plane
  (replaces the reference's ``cuda_shared_memory``): regions backed by
  host-mapped buffers bridged to jax.Array / XLA device buffers via DLPack.
- ``client_tpu.server``: an in-process KServe v2 server with a JAX/XLA
  execution backend (the reference has no server; ours makes the framework
  self-contained and testable on a TPU VM).
- ``client_tpu.models`` / ``client_tpu.ops`` / ``client_tpu.parallel``: the
  JAX model zoo, jitted data-plane ops, and device-mesh sharding used by the
  server backend.

Reference parity map: see SURVEY.md at the repo root.
"""

__version__ = "0.1.0"
