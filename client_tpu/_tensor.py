"""Protocol-neutral tensor value model: InferInput / InferRequestedOutput.

One shared implementation backs both the HTTP and GRPC namespaces (the
reference duplicates these per protocol: http/_infer_input.py:106-242,
grpc/_infer_input.py; http/_requested_output.py, grpc/_requested_output.py).
Protocol encoders consume the private accessors.

TPU-first additions over the reference:
- ``set_data_from_dlpack``: zero-copy ingestion of any ``__dlpack__`` producer
  on CPU (jax host arrays, torch CPU tensors) — no intermediate numpy copy.
- jax.Array values are accepted everywhere numpy arrays are; device arrays are
  fetched with a single device->host transfer.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .utils import (
    InferenceServerException,
    np_to_triton_dtype,
    serialize_bf16_tensor,
    serialize_byte_tensor,
    triton_to_np_dtype,
)


def _is_jax_array(t: Any) -> bool:
    mod = type(t).__module__
    return mod.startswith("jax") or mod.startswith("jaxlib")


def _release_quietly(lease) -> None:
    """Drop one lease reference, tolerating a lease some OTHER holder
    (e.g. ``InferResult.release_arena``) already fully released — the
    convenience release paths are ensure-gone, not strict handoffs."""
    from .arena import ArenaError

    try:
        lease.release()
    except ArenaError:
        pass


class ArenaOutputsMixin:
    """The result-side arena surface shared by the HTTP and GRPC
    ``InferResult`` classes: the frontends attach output leases here when
    requested outputs were bound via ``ArenaLease.bind_output`` /
    ``ShmArena.request_output``, and ``as_numpy`` serves zero-copy views
    through :meth:`_arena_lease_for`."""

    _arena_output_leases: Optional[Dict[str, Any]] = None
    _arena_released = False

    def _arena_lease_for(self, name: str):
        leases = self._arena_output_leases
        return leases.get(name) if leases else None

    def release_arena(self) -> None:
        """Release every output lease bound to this result (idempotent).
        The lease map is kept so a later ``as_numpy`` on one of these
        outputs raises the typed ``ArenaLeaseReleased`` instead of
        silently returning None."""
        if self._arena_released:
            return
        self._arena_released = True
        for lease in (self._arena_output_leases or {}).values():
            _release_quietly(lease)


def _to_host_ndarray(tensor: Any) -> np.ndarray:
    """Materialize ``tensor`` on host as a numpy ndarray with minimal copies."""
    if isinstance(tensor, np.ndarray):
        return tensor
    if _is_jax_array(tensor):
        # np.asarray on a committed device array performs one D2H transfer and
        # is zero-copy for host-resident arrays.
        return np.asarray(tensor)
    if hasattr(tensor, "__dlpack__"):
        try:
            return np.from_dlpack(tensor)
        except Exception:
            pass
    return np.asarray(tensor)


class InferInput:
    """An input tensor for an inference request."""

    # arena fast path (client_tpu.arena): a lease staged via
    # ``set_data_from_numpy(..., arena=...)`` or ``ArenaLease.bind_input``;
    # re-staging the input releases it
    _arena_lease = None

    def __init__(self, name: str, shape: Sequence[int], datatype: str):
        self._name = name
        self._shape = list(shape)
        self._datatype = datatype
        self._parameters: Dict[str, Any] = {}
        self._raw_data: Optional[bytes] = None
        self._json_data: Optional[List[Any]] = None

    # -- introspection -----------------------------------------------------
    def name(self) -> str:
        return self._name

    def datatype(self) -> str:
        return self._datatype

    def shape(self) -> List[int]:
        return self._shape

    def set_shape(self, shape: Sequence[int]) -> "InferInput":
        self._shape = list(shape)
        return self

    # -- data paths --------------------------------------------------------
    def set_data_from_numpy(self, input_tensor, binary_data: bool = True,
                            arena=None) -> "InferInput":
        """Stage tensor contents in the request (binary blob or JSON list).

        ``arena``: a :class:`client_tpu.arena.ShmArena` — the tensor is
        written ONCE straight into a leased slab and the input binds it via
        shared-memory params (no bytes on the wire); the region's server
        registration is ensured (and cached) at ``infer()`` time. The
        input holds the lease until re-staged or
        :meth:`release_arena_lease` is called."""
        input_tensor = _to_host_ndarray(input_tensor)
        dtype = np_to_triton_dtype(input_tensor.dtype)
        if dtype != self._datatype:
            raise InferenceServerException(
                f"got unexpected datatype {dtype} from numpy array; expected {self._datatype}"
            )
        self._validate_shape(input_tensor)

        if arena is not None:
            if not binary_data:
                raise InferenceServerException(
                    "arena staging requires binary_data=True")
            # BYTES/BF16 serialize exactly once (the payload sizes the
            # lease AND is the write); fixed-width dtypes skip the staging
            # copy entirely — write_numpy copies straight into the slab
            if self._datatype == "BYTES":
                s = serialize_byte_tensor(input_tensor)
                payload = s.item() if s.size else b""
            elif self._datatype == "BF16":
                s = serialize_bf16_tensor(input_tensor)
                payload = s.item() if s.size else b""
            else:
                payload = None
            nbytes = input_tensor.nbytes if payload is None else len(payload)
            lease = arena.lease(max(nbytes, 1))
            try:
                if payload is None:
                    lease.write_numpy(input_tensor)
                else:
                    lease.write(payload)
            except BaseException:
                lease.release()
                raise
            self._json_data = None
            self._raw_data = None
            lease.bind_input(self)  # releases any previous lease
            return self

        self._clear_shared_memory_params()
        self._json_data = None
        self._raw_data = None

        if not binary_data:
            if self._datatype == "BF16":
                raise InferenceServerException(
                    "BF16 inputs must use binary_data=True (no JSON representation)"
                )
            if self._datatype == "BYTES":
                data = []
                for obj in np.nditer(input_tensor, flags=["refs_ok"], order="C"):
                    item = obj.item()
                    if isinstance(item, bytes):
                        try:
                            data.append(item.decode("utf-8"))
                        except UnicodeDecodeError:
                            raise InferenceServerException(
                                "BYTES input with non-UTF8 data requires binary_data=True"
                            )
                    else:
                        data.append(str(item))
                self._json_data = data
            else:
                self._json_data = [v.item() for v in np.nditer(input_tensor, order="C")]
            return self

        if self._datatype == "BYTES":
            serialized = serialize_byte_tensor(input_tensor)
            self._raw_data = serialized.item() if serialized.size > 0 else b""
        elif self._datatype == "BF16":
            serialized = serialize_bf16_tensor(input_tensor)
            self._raw_data = serialized.item() if serialized.size > 0 else b""
        else:
            self._raw_data = np.ascontiguousarray(input_tensor).tobytes()
        self._parameters.pop("binary_data_size", None)
        return self

    def set_data_from_dlpack(self, tensor: Any) -> "InferInput":
        """Zero-copy ingest of a ``__dlpack__`` producer (jax, torch, numpy).

        Host tensors are wrapped without a copy; accelerator-resident tensors
        incur exactly one device->host transfer.
        """
        if _is_jax_array(tensor):
            arr = np.asarray(tensor)
        else:
            arr = np.from_dlpack(tensor)
        expected = triton_to_np_dtype(self._datatype)
        if expected is not None and arr.dtype != np.dtype(expected):
            raise InferenceServerException(
                f"dlpack tensor has dtype {arr.dtype}, expected "
                f"{np.dtype(expected)} for {self._datatype}"
            )
        self._validate_shape(arr)
        self._clear_shared_memory_params()
        self._json_data = None
        if arr.flags["C_CONTIGUOUS"]:
            self._raw_data = memoryview(arr.reshape(-1).view(np.uint8))
        else:
            self._raw_data = np.ascontiguousarray(arr).tobytes()
        return self

    def set_shared_memory(self, region_name: str, byte_size: int, offset: int = 0) -> "InferInput":
        """Reference tensor contents in a pre-registered shared-memory region."""
        self.release_arena_lease()
        self._json_data = None
        self._raw_data = None
        self._parameters.pop("binary_data_size", None)
        self._parameters["shared_memory_region"] = region_name
        self._parameters["shared_memory_byte_size"] = byte_size
        if offset != 0:
            self._parameters["shared_memory_offset"] = offset
        return self

    def release_arena_lease(self) -> "InferInput":
        """Release the arena lease this input holds (no-op without one;
        idempotent even if the lease was already released elsewhere).
        Called automatically whenever the input is re-staged."""
        lease = self._arena_lease
        if lease is not None:
            self._arena_lease = None
            _release_quietly(lease)
        return self

    # -- encoder-facing private API ---------------------------------------
    def _validate_shape(self, tensor: np.ndarray) -> None:
        expected = 1
        for d in self._shape:
            expected *= d
        if tensor.size != expected:
            raise InferenceServerException(
                f"got {tensor.size} elements for input '{self._name}', "
                f"expected {expected} (shape {self._shape})"
            )

    def _clear_shared_memory_params(self) -> None:
        self.release_arena_lease()
        for k in ("shared_memory_region", "shared_memory_byte_size", "shared_memory_offset"):
            self._parameters.pop(k, None)

    def _get_binary_data(self) -> Optional[bytes]:
        return self._raw_data

    def _get_tensor_json(self) -> Dict[str, Any]:
        """The HTTP JSON descriptor for this input."""
        tensor: Dict[str, Any] = {
            "name": self._name,
            "shape": self._shape,
            "datatype": self._datatype,
        }
        params = dict(self._parameters)
        if self._raw_data is not None:
            params["binary_data_size"] = len(self._raw_data)
        if params:
            tensor["parameters"] = params
        if self._json_data is not None:
            tensor["data"] = self._json_data
        return tensor

    def _shared_memory_params(self) -> Optional[Tuple[str, int, int]]:
        region = self._parameters.get("shared_memory_region")
        if region is None:
            return None
        return (
            region,
            self._parameters.get("shared_memory_byte_size", 0),
            self._parameters.get("shared_memory_offset", 0),
        )


class InferRequestedOutput:
    """A requested output tensor with optional classification / shm placement."""

    # arena fast path: a lease bound via ``ArenaLease.bind_output`` /
    # ``ShmArena.request_output``; the frontends attach it to the
    # InferResult so ``as_numpy`` serves a zero-copy view over the slab
    _arena_lease = None

    def __init__(self, name: str, binary_data: bool = True, class_count: int = 0):
        self._name = name
        self._binary_data = binary_data
        self._class_count = class_count
        self._parameters: Dict[str, Any] = {}

    def name(self) -> str:
        return self._name

    def set_shared_memory(self, region_name: str, byte_size: int, offset: int = 0) -> "InferRequestedOutput":
        self.release_arena_lease()
        self._parameters["shared_memory_region"] = region_name
        self._parameters["shared_memory_byte_size"] = byte_size
        if offset != 0:
            self._parameters["shared_memory_offset"] = offset
        return self

    def unset_shared_memory(self) -> "InferRequestedOutput":
        self.release_arena_lease()
        for k in ("shared_memory_region", "shared_memory_byte_size", "shared_memory_offset"):
            self._parameters.pop(k, None)
        return self

    def release_arena_lease(self) -> "InferRequestedOutput":
        """Release the arena lease this output holds (no-op without one;
        idempotent even if the lease was already released elsewhere)."""
        lease = self._arena_lease
        if lease is not None:
            self._arena_lease = None
            _release_quietly(lease)
        return self

    # -- encoder-facing private API ---------------------------------------
    def _in_shared_memory(self) -> bool:
        return "shared_memory_region" in self._parameters

    def _shared_memory_params(self) -> Optional[Tuple[str, int, int]]:
        region = self._parameters.get("shared_memory_region")
        if region is None:
            return None
        return (
            region,
            self._parameters.get("shared_memory_byte_size", 0),
            self._parameters.get("shared_memory_offset", 0),
        )

    def _get_tensor_json(self) -> Dict[str, Any]:
        tensor: Dict[str, Any] = {"name": self._name}
        params = dict(self._parameters)
        if self._class_count != 0:
            params["classification"] = self._class_count
        if not self._in_shared_memory():
            params["binary_data"] = self._binary_data
        if params:
            tensor["parameters"] = params
        return tensor
