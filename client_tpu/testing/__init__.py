"""Test-support subsystems shipped with the framework (importable by user
test suites, not only this repo's): currently the chaos fault-injection
proxy that proves the resilience layer end-to-end, and the cell-scale
``ChaosCell`` grouping that faults a whole replica group atomically."""

from .chaos import ChaosCell, ChaosProxy, Fault

__all__ = ["ChaosCell", "ChaosProxy", "Fault"]
