"""Test-support subsystems shipped with the framework (importable by user
test suites, not only this repo's): the chaos fault-injection proxy that
proves the resilience layer end-to-end, the cell-scale ``ChaosCell``
grouping that faults a whole replica group atomically, and the seeded
byzantine server wrapper whose responses LIE (healthy transport, corrupt
payloads) to prove the integrity layer against live wire bytes."""

from .byzantine import ByzantineHttpServer, ByzantinePlan
from .chaos import ChaosCell, ChaosProxy, Fault

__all__ = ["ByzantineHttpServer", "ByzantinePlan", "ChaosCell",
           "ChaosProxy", "Fault"]
