"""Test-support subsystems shipped with the framework (importable by user
test suites, not only this repo's): currently the chaos fault-injection
proxy that proves the resilience layer end-to-end."""

from .chaos import ChaosProxy, Fault

__all__ = ["ChaosProxy", "Fault"]
