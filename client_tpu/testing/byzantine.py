"""Byzantine fault server: a live v2 HTTP server that LIES.

The chaos proxy (:mod:`client_tpu.testing.chaos`) breaks transport —
resets, stalls, blackholes — which the resilience layer already turns
into typed retryable faults. A byzantine replica is the opposite
failure: transport is perfectly healthy, health probes answer ready,
the breaker records successes — and the *payload* is wrong. This
module wraps the in-process HTTP server with a deterministic, seeded
corruption layer so the integrity subsystem (contract validation,
digests, quarantine) can be proven against live wire bytes instead of
hand-built mocks.

Fault vocabulary (``ByzantinePlan.kinds``):

- ``shape_lie``    — an output's JSON ``shape`` grows one element on its
  last axis while the payload stays put (size arithmetic and the cached
  metadata contract both catch it).
- ``dtype_lie``    — an output's ``datatype`` is swapped for a wider type
  (INT32→INT64 style: payload arithmetic catches it without metadata).
- ``truncate``     — the binary tail loses its final third (Content-Length
  is consistent with the SHORTENED body, so only the header-claim vs
  buffer-span check can notice).
- ``bit_flip``     — one seeded bit flips in the binary tail; every size
  and header claim stays consistent. Deliberately contract-UNdetectable:
  only a data-plane digest or a value check catches it (docs/integrity.md
  "detectability").
- ``wrong_id``     — the response echoes a request_id that is not yours.
- ``garbage_json`` — the JSON response header is replaced with invalid
  UTF-8 garbage (exercises the typed-error-not-UnicodeDecodeError path).
- ``dup_index``    — an SSE generate event is emitted twice with the same
  explicit ``index``.
- ``drop_index``   — an SSE generate event's ``index`` skips a value.

Determinism: one ``random.Random(seed)`` drives every choice (which
fault fires when ``kinds`` has several, which output entry is mutated,
which bit flips), and ``every``/``limit`` schedule which responses are
corrupted at all — so a bench replay with the same seed corrupts the
same responses the same way, run after run.

Usage::

    srv = ByzantineHttpServer(ServerCore(default_model_zoo()),
                              kinds=("shape_lie",), seed=7, every=1)
    srv.start()
    client = InferenceServerClient(srv.url)   # every response now lies
"""

from __future__ import annotations

import json
import random
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..server.core import ServerCore
from ..server.http_server import (
    HttpInferenceServer,
    _generate_core_request,
    _generate_event,
    _Handler,
    _sse_event,
    _TrackingHTTPServer,
    encode_infer_response,
    infer_request_encoding_prefs,
    parse_infer_request,
)

__all__ = ["ByzantineHttpServer", "ByzantinePlan", "FAULT_KINDS"]

FAULT_KINDS = (
    "shape_lie", "dtype_lie", "truncate", "bit_flip",
    "wrong_id", "garbage_json", "dup_index", "drop_index",
)

# unary faults corrupt an encoded infer response; stream faults corrupt
# the SSE event sequence — a plan may mix both, each path draws only
# from the kinds it can express
_UNARY_KINDS = ("shape_lie", "dtype_lie", "truncate", "bit_flip",
                "wrong_id", "garbage_json")
_STREAM_KINDS = ("dup_index", "drop_index")

# dtype_lie swaps for a WIDER type so the size arithmetic disagrees
# without any cached metadata (a same-size swap like INT32→FP32 is only
# metadata-detectable; use note_metadata tests for that shape)
_DTYPE_LIES = {
    "INT8": "INT16", "INT16": "INT32", "INT32": "INT64",
    "UINT8": "UINT16", "UINT16": "UINT32", "UINT32": "UINT64",
    "FP16": "FP32", "BF16": "FP32", "FP32": "FP64", "BOOL": "INT16",
    "INT64": "INT32", "FP64": "FP32", "UINT64": "UINT32",
}


class ByzantinePlan:
    """Deterministic corruption schedule shared by a server's handlers.

    ``every``/``limit`` mirror the chaos :class:`~client_tpu.testing.chaos.Fault`
    semantics: the ``every``-th response (1-based) is corrupted, at most
    ``limit`` times total (``None`` = unlimited). ``kinds`` restricts the
    vocabulary; with several kinds the seeded rng picks one per corrupted
    response."""

    def __init__(
        self,
        kinds: Sequence[str] = _UNARY_KINDS,
        seed: int = 0,
        every: int = 1,
        limit: Optional[int] = None,
    ):
        for kind in kinds:
            if kind not in FAULT_KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r} (one of {FAULT_KINDS})")
        if every < 1:
            raise ValueError("every must be >= 1")
        self.kinds = tuple(kinds)
        self.seed = seed
        self.every = every
        self.limit = limit
        self._rng = random.Random(seed)
        self._responses = 0
        self._applied = 0
        self._lock = threading.Lock()
        # what actually fired, for bench provenance: [(response_index, kind)]
        self.log: List[Tuple[int, str]] = []

    def next_fault(self, pool: Sequence[str]) -> Optional[str]:
        """The fault for the next response, or None (honest). ``pool``
        narrows to the kinds the calling path can express."""
        with self._lock:
            self._responses += 1
            if self.limit is not None and self._applied >= self.limit:
                return None
            if self._responses % self.every != 0:
                return None
            candidates = [k for k in self.kinds if k in pool]
            if not candidates:
                return None
            self._applied += 1
            kind = self._rng.choice(candidates)
            self.log.append((self._responses, kind))
            return kind

    def rng(self) -> random.Random:
        return self._rng

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"responses": self._responses, "corrupted": self._applied}


def _corrupt_unary(
    kind: str, body: bytes, json_size: Optional[int], rng: random.Random,
) -> Tuple[bytes, Optional[int]]:
    """Apply one unary fault to an encoded (body, json_header_length)."""
    hdr_bytes = body[:json_size] if json_size is not None else body
    tail = body[json_size:] if json_size is not None else b""
    if kind == "garbage_json":
        # invalid JSON *and* invalid UTF-8: the client must raise a typed
        # error, not json.JSONDecodeError or UnicodeDecodeError
        garbage = b'{"model_name": \xff\xfe\x00 not json'
        size = len(garbage) if json_size is not None else None
        return garbage + tail, size
    header = json.loads(hdr_bytes)
    outs = [o for o in header.get("outputs", []) if "data" in o
            or "binary_data_size" in str(o.get("parameters", {}))
            or o.get("parameters", {}).get("binary_data_size") is not None]
    outs = outs or header.get("outputs", [])
    if kind == "wrong_id":
        header["id"] = (header.get("id") or "rq") + "-byz"
    elif kind == "shape_lie" and outs:
        entry = rng.choice(outs)
        shape = entry.get("shape") or [1]
        shape[-1] = int(shape[-1]) + 1
    elif kind == "dtype_lie" and outs:
        entry = rng.choice(outs)
        entry["datatype"] = _DTYPE_LIES.get(entry.get("datatype", ""),
                                            "INT64")
    new_hdr = json.dumps(header, separators=(",", ":")).encode("utf-8")
    if kind == "truncate":
        if tail:
            tail = tail[: len(tail) - max(1, len(tail) // 3)]
        elif len(new_hdr) > 4:
            new_hdr = new_hdr[:-4]  # JSON-only response: torn JSON
    elif kind == "bit_flip":
        if tail:
            buf = bytearray(tail)
            buf[rng.randrange(len(buf))] ^= 1 << rng.randrange(8)
            tail = bytes(buf)
        else:
            # JSON data path: corrupt one value in place — every claim
            # stays consistent, only a value check can tell
            for entry in header.get("outputs", []):
                data = entry.get("data")
                if data:
                    idx = rng.randrange(len(data))
                    if isinstance(data[idx], (int, float)):
                        data[idx] = data[idx] + 1
                        break
            new_hdr = json.dumps(header, separators=(",", ":")).encode()
    size = len(new_hdr) if json_size is not None else None
    return new_hdr + tail, size


class _ByzantineHandler(_Handler):
    """The honest handler with a corruption step between encode and send."""

    plan: ByzantinePlan  # set by server factory

    def _do_infer(self, model_name: str, model_version: str, body: bytes):
        header_length = self.headers.get("Inference-Header-Content-Length")
        request = parse_infer_request(
            body, int(header_length) if header_length is not None else None)
        requested, binary_default = infer_request_encoding_prefs(request)
        responses = self.core.infer(model_name, model_version, request)
        body_out, json_size = encode_infer_response(
            responses[0], requested, binary_default)
        fault = self.plan.next_fault(_UNARY_KINDS)
        if fault is not None:
            body_out, json_size = _corrupt_unary(
                fault, body_out, json_size, self.plan.rng())
        headers = {"Content-Type": "application/json"}
        if json_size is not None:
            headers = {
                "Content-Type": "application/octet-stream",
                "Inference-Header-Content-Length": str(json_size),
            }
        self._send(200, body_out, headers)

    def _do_generate(self, model_name: str, model_version: str,
                     body: bytes, stream: bool):
        if not stream:
            return super()._do_generate(model_name, model_version, body,
                                        stream)
        # streamed: the honest SSE loop, but every event carries an
        # explicit monotone "index" (as real decoupled servers emit) so
        # dup_index/drop_index have something to corrupt
        payload = json.loads(body) if body else {}
        core_req = _generate_core_request(
            self.core.model(model_name, model_version), payload)
        gen = self.core.infer_stream(model_name, model_version, core_req)

        def chunk(data: bytes) -> None:
            self.wfile.write(b"%x\r\n%s\r\n" % (len(data), data))

        try:
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            self.wfile.flush()
            index = 0
            for item in gen:
                event = _generate_event(item)
                # models that don't emit an index tensor themselves get a
                # monotone one injected (as real decoupled servers emit),
                # so the faults below always have an index to corrupt
                if not any(k in event
                           for k in ("INDEX", "index", "sequence_index")):
                    event["index"] = index
                index += 1
                fault = self.plan.next_fault(_STREAM_KINDS)
                if fault == "drop_index":
                    continue  # event swallowed whole: a gap on the wire
                chunk(_sse_event(event))
                if fault == "dup_index":
                    chunk(_sse_event(dict(event)))  # delivered twice
            self.wfile.write(b"0\r\n\r\n")
        except OSError:
            self.close_connection = True
        except Exception as e:
            try:
                chunk(_sse_event({"error": str(e)}))
                self.wfile.write(b"0\r\n\r\n")
            except Exception:
                pass
            self.close_connection = True
        finally:
            gen.close()


class ByzantineHttpServer(HttpInferenceServer):
    """An in-process v2 HTTP server whose responses are corrupted per a
    seeded :class:`ByzantinePlan`. Drop-in replacement for
    :class:`~client_tpu.server.http_server.HttpInferenceServer` — same
    ``url``/``start``/``stop``/``close`` surface, so a pool test points
    one replica of three here and the other two at honest servers."""

    def __init__(
        self,
        core: ServerCore,
        plan: Optional[ByzantinePlan] = None,
        port: int = 0,
        verbose: bool = False,
        **plan_kwargs: Any,
    ):
        self.core = core
        self.plan = plan if plan is not None else ByzantinePlan(**plan_kwargs)
        handler = type(
            "BoundByzantineHandler", (_ByzantineHandler,),
            {"core": core, "plan": self.plan})
        self._httpd = _TrackingHTTPServer(("127.0.0.1", port), handler)
        self._httpd.verbose = verbose
        self._httpd.daemon_threads = True
        self._thread = None
