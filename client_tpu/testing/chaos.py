"""In-process TCP fault-injection proxy (toxiproxy-style).

A localhost TCP proxy that sits between a client and a real server and
injects transport faults on command, so ``tests/test_resilience.py`` can
prove retry / circuit-breaker / stream-reconnect behavior against live
HTTP and GRPC servers instead of mocks. Works for any byte protocol —
it never parses what it forwards.

Fault vocabulary (see :class:`Fault`):

- ``latency``   — delay every forwarded chunk by ``latency_s``.
- ``reset``     — hard TCP reset (RST via SO_LINGER 0) once ``after_bytes``
  total bytes have crossed the proxy in either direction. ``after_bytes=0``
  resets immediately after accept (connect succeeds, then dies).
- ``blackhole`` — accept, read and discard client bytes, never connect
  upstream, never answer (exercises read-timeout paths).
- ``stall``     — forward the request, deliver ``after_bytes`` of the
  response, then stop forwarding while holding the socket open
  (partial-write-then-stall).
- ``flap``      — reset at accept on every ``every``-th connection
  (connection flapping).
- ``corrupt``   — deliver the response intact up to ``after_bytes``
  (skip the HTTP headers), then corrupt the next ``corrupt_bytes``
  response bytes: seeded deterministic bit-flips
  (``corrupt_mode="flip"``, the default) or a clean FIN truncation
  (``corrupt_mode="truncate"`` — the body ends short of its
  Content-Length). Transport stays perfectly healthy either way; only
  the payload lies — the integrity layer's problem, not the retry
  layer's.

``Fault.limit`` bounds how many connections a fault is applied to
(``None`` = unlimited) — set ``limit=1`` to fault exactly the first
connection and let retries through, or clear ``proxy.fault = None`` to
heal. ``reset_active()`` RSTs currently-established connections (kills a
live GRPC stream mid-flight).

Usage::

    proxy = ChaosProxy("127.0.0.1", server.port).start()
    client = InferenceServerClient(proxy.url)
    proxy.fault = Fault("reset", after_bytes=64, limit=1)
    ...
    proxy.stop()
"""

from __future__ import annotations

import random
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["ChaosCell", "ChaosProxy", "Fault"]

_KINDS = ("latency", "reset", "blackhole", "stall", "flap", "corrupt")


class Fault:
    """One fault rule applied to connections accepted while it is set."""

    def __init__(
        self,
        kind: str,
        after_bytes: int = 0,
        latency_s: float = 0.0,
        every: int = 1,
        limit: Optional[int] = None,
        corrupt_bytes: int = 1,
        corrupt_mode: str = "flip",
        seed: int = 0,
    ):
        if kind not in _KINDS:
            raise ValueError(f"unknown fault kind {kind!r} (one of {_KINDS})")
        if every < 1:
            raise ValueError("every must be >= 1")
        if corrupt_mode not in ("flip", "truncate"):
            raise ValueError(
                f"corrupt_mode must be 'flip' or 'truncate', "
                f"not {corrupt_mode!r}")
        if corrupt_bytes < 1:
            raise ValueError("corrupt_bytes must be >= 1")
        self.kind = kind
        self.after_bytes = after_bytes
        self.latency_s = latency_s
        self.every = every
        self.limit = limit
        self.corrupt_bytes = corrupt_bytes
        self.corrupt_mode = corrupt_mode
        self.seed = seed
        # seeded once per Fault: the same rule corrupts the same offsets
        # with the same bit patterns, run after run (bench replayability)
        self._rng = random.Random(seed)
        self._applied = 0
        self._lock = threading.Lock()

    def claim(self, conn_index: int) -> bool:
        """Whether this connection (1-based accept index) gets the fault."""
        with self._lock:
            if self.limit is not None and self._applied >= self.limit:
                return False
            if conn_index % self.every != 0:
                return False
            self._applied += 1
            return True

    def __repr__(self) -> str:
        return (f"Fault({self.kind!r}, after_bytes={self.after_bytes}, "
                f"latency_s={self.latency_s}, every={self.every}, "
                f"limit={self.limit})")


def _rst_close(sock: socket.socket) -> None:
    """Close with an RST instead of FIN (SO_LINGER onoff=1, linger=0)."""
    try:
        sock.setsockopt(
            socket.SOL_SOCKET, socket.SO_LINGER, struct.pack("ii", 1, 0))
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class _Connection:
    """One proxied connection: two pump threads + shared fault state."""

    def __init__(self, proxy: "ChaosProxy", client: socket.socket,
                 fault: Optional[Fault]):
        self.proxy = proxy
        self.client = client
        self.fault = fault
        self.upstream: Optional[socket.socket] = None
        self.total_bytes = 0
        self._lock = threading.Lock()
        self._dead = False
        self._threads: List[threading.Thread] = []
        # corrupt-fault state (s2c): where the first response's header
        # block ends and how many body bytes have been forwarded since
        self._hdr_done = False
        self._hdr_scan = b""
        self._body_seen = 0

    def run(self) -> None:
        fault = self.fault
        if fault is not None and fault.kind == "flap":
            self.proxy._note_fault()
            _rst_close(self.client)
            return
        if fault is not None and fault.kind == "blackhole":
            self.proxy._note_fault()
            # own thread: swallowing this client until it gives up must not
            # block the accept loop (later connections would stall unproxied)
            t = threading.Thread(
                target=self._blackhole, name="chaos_blackhole", daemon=True)
            self._threads.append(t)
            t.start()
            return
        try:
            self.upstream = socket.create_connection(
                (self.proxy.upstream_host, self.proxy.upstream_port),
                timeout=10,
            )
            self.upstream.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # short poll timeout, NOT blocking recv: a pump blocked in
            # recv() pins the fd in the kernel, deferring kill()'s RST
            # until data arrives — which for an idle connection is never
            self.upstream.settimeout(0.2)
            self.client.settimeout(0.2)
        except OSError:
            _rst_close(self.client)
            return
        if fault is not None:
            self.proxy._note_fault()
        for src, dst, direction in (
            (self.client, self.upstream, "c2s"),
            (self.upstream, self.client, "s2c"),
        ):
            t = threading.Thread(
                target=self._pump, args=(src, dst, direction),
                name=f"chaos_{direction}", daemon=True,
            )
            self._threads.append(t)
            t.start()

    def _blackhole(self) -> None:
        self.client.settimeout(0.2)
        try:
            while not self._dead:
                try:
                    if not self.client.recv(65536):
                        break
                except socket.timeout:
                    continue
        except OSError:
            pass
        finally:
            _rst_close(self.client)

    def _pump(self, src: socket.socket, dst: socket.socket, direction: str) -> None:
        fault = self.fault
        try:
            while True:
                try:
                    data = src.recv(65536)
                except socket.timeout:
                    if self._dead:
                        return
                    continue
                while self.proxy.pause_forwarding and not self._dead:
                    time.sleep(0.005)  # freeze established flows on command
                if self._dead:
                    return
                if not data:
                    try:
                        dst.shutdown(socket.SHUT_WR)  # propagate half-close
                    except OSError:
                        pass
                    return
                if fault is not None and fault.kind == "latency":
                    time.sleep(fault.latency_s)
                if fault is not None and fault.kind == "reset":
                    with self._lock:
                        self.total_bytes += len(data)
                        tripped = self.total_bytes >= fault.after_bytes
                    if tripped:
                        self.kill()
                        return
                if fault is not None and fault.kind == "corrupt" and direction == "s2c":
                    data, close_after = self._corrupt_s2c(data, fault)
                    if data:
                        dst.sendall(data)
                    if close_after:
                        # clean FIN: the client sees a short body against
                        # its Content-Length — a payload lie, not a reset
                        try:
                            dst.shutdown(socket.SHUT_WR)
                        except OSError:
                            pass
                        return
                    continue
                if fault is not None and fault.kind == "stall" and direction == "s2c":
                    with self._lock:
                        budget = fault.after_bytes - self.total_bytes
                        self.total_bytes += len(data)
                    if budget <= 0:
                        # hold the socket open, forward nothing more
                        while not self._dead:
                            time.sleep(0.05)
                        return
                    data = data[:budget]
                dst.sendall(data)
        except OSError:
            self.kill()

    def _corrupt_s2c(self, data: bytes, fault: Fault) -> "Tuple[bytes, bool]":
        """Apply the corrupt fault to one s2c chunk.

        Returns ``(bytes_to_forward, close_after)``. The first response's
        HTTP header block passes through untouched (found by scanning for
        the first blank line, spanning chunk boundaries); body bytes then
        count toward the corruption window ``[after_bytes,
        after_bytes + corrupt_bytes)``. ``flip`` XORs each window byte
        with a seeded nonzero mask and forwards everything else intact —
        sizes, framing and Content-Length all stay consistent, only the
        payload lies. ``truncate`` forwards up to the window and then
        FINs, a short read against the declared Content-Length."""
        if not self._hdr_done:
            merged = self._hdr_scan + data
            pos = merged.find(b"\r\n\r\n")
            if pos < 0:
                self._hdr_scan = merged[-3:]
                return data, False  # still inside the header block
            self._hdr_done = True
            body_at = pos + 4 - len(self._hdr_scan)
            self._hdr_scan = b""
            head, body = data[:body_at], data[body_at:]
        else:
            head, body = b"", data
        lo = fault.after_bytes - self._body_seen
        hi = lo + fault.corrupt_bytes
        self._body_seen += len(body)
        if fault.corrupt_mode == "truncate":
            if lo >= len(body):
                return head + body, False  # window not reached yet
            return head + body[:max(0, lo)], True
        out = bytearray(body)
        for j in range(max(0, lo), min(len(out), hi)):
            out[j] ^= fault._rng.randrange(1, 256)
        return head + bytes(out), False

    def kill(self) -> None:
        with self._lock:
            if self._dead:
                return
            self._dead = True
        _rst_close(self.client)
        if self.upstream is not None:
            _rst_close(self.upstream)


class ChaosProxy:
    """A localhost TCP proxy with runtime-injectable faults.

    ``fault`` may be swapped at any time; it applies to connections
    accepted from then on (use :meth:`reset_active` to also kill
    already-established ones). Thread-per-pump keeps it simple and is
    plenty for test traffic.
    """

    def __init__(self, upstream_host: str, upstream_port: int,
                 listen_port: int = 0):
        self.upstream_host = upstream_host
        self.upstream_port = upstream_port
        self.fault: Optional[Fault] = None
        # freeze established connections (bytes buffer, nothing forwarded)
        # without killing them — pairs with reset_active() to make in-flight
        # requests provably un-delivered before the connection dies
        self.pause_forwarding = False
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", listen_port))
        self._listener.listen(128)
        self._accept_thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()
        self._lock = threading.Lock()
        self._conns: List[_Connection] = []
        self.stats: Dict[str, int] = {"connections": 0, "faulted": 0}

    @property
    def port(self) -> int:
        return self._listener.getsockname()[1]

    @property
    def url(self) -> str:
        return f"127.0.0.1:{self.port}"

    def start(self) -> "ChaosProxy":
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="chaos_accept", daemon=True)
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._stopped.set()
        try:
            self._listener.close()
        except OSError:
            pass
        self.reset_active()

    def __enter__(self) -> "ChaosProxy":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def reset_active(self) -> None:
        """RST every currently-established proxied connection (kills live
        streams mid-flight; new connections are unaffected)."""
        with self._lock:
            conns, self._conns = self._conns, []
        for conn in conns:
            conn.kill()

    def heal(self) -> None:
        """Clear the fault rule; subsequent connections pass through clean."""
        self.fault = None

    def _note_fault(self) -> None:
        with self._lock:
            self.stats["faulted"] += 1

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                client, _ = self._listener.accept()
            except OSError:
                return
            client.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self.stats["connections"] += 1
                index = self.stats["connections"]
            fault = self.fault
            if fault is not None and not fault.claim(index):
                fault = None
            conn = _Connection(self, client, fault)
            with self._lock:
                self._conns = [c for c in self._conns if not c._dead]
                self._conns.append(conn)
            conn.run()


class ChaosCell:
    """Cell-scale fault orchestration: fault a GROUP of proxies as one.

    A multi-cell federation test needs to kill a whole cell — every
    replica's proxy, in one call, mid-replay — not flip proxies one by
    one while traffic threads the gaps. ``ChaosCell`` groups existing
    :class:`ChaosProxy` instances (one per replica of the "cell") and
    applies each fault verb to all of them atomically: the fault rule is
    installed on EVERY proxy first, and only then are the established
    connections of every proxy reset — so no request accepted after the
    call sees a healthy replica of a cell that is supposed to be dead.

    Reuses the per-proxy fault vocabulary verbatim::

        cell = ChaosCell([proxy_a1, proxy_a2])
        cell.blackhole()        # the whole cell goes dark mid-flight
        cell.heal()             # and comes back
        cell.kill()             # RST storm: reset at accept + live RSTs
        cell.latency(0.05)      # uniform 50 ms added per forwarded chunk
        cell.flap(3)            # every 3rd connection RSTs at accept

    Independent of the federation layer: any test driving a pool (or a
    bare client) across several proxies can group them."""

    def __init__(self, proxies: Sequence[ChaosProxy]):
        if not proxies:
            raise ValueError("a chaos cell needs at least one proxy")
        self.proxies: List[ChaosProxy] = list(proxies)

    @property
    def urls(self) -> List[str]:
        return [p.url for p in self.proxies]

    def _apply(self, fault_factory, reset_active: bool) -> None:
        """Install one independently-constructed Fault per proxy (a
        shared Fault object would pool its ``limit``/counters across the
        cell), then reset established connections — faults first, so a
        connection racing the call lands on an already-faulted proxy."""
        for proxy in self.proxies:
            proxy.fault = fault_factory()
        if reset_active:
            for proxy in self.proxies:
                proxy.reset_active()

    def blackhole(self, reset_active: bool = True) -> None:
        """The whole cell goes dark: new connections are accepted and
        swallowed (never answered), established ones are RST (unless
        ``reset_active=False`` — then in-flight requests run out their
        own timeouts, the slow-blackhole shape)."""
        self._apply(lambda: Fault("blackhole"), reset_active)

    def kill(self) -> None:
        """RST storm: every new connection resets immediately after
        accept, every established one resets now."""
        self._apply(lambda: Fault("reset", after_bytes=0), True)

    def latency(self, latency_s: float) -> None:
        """Uniform added latency per forwarded chunk, cell-wide."""
        self._apply(
            lambda: Fault("latency", latency_s=latency_s), False)

    def flap(self, every: int = 2) -> None:
        """Connection flapping cell-wide (every ``every``-th accept
        RSTs)."""
        self._apply(lambda: Fault("flap", every=every), False)

    def heal(self, reset_active: bool = False) -> None:
        """Clear every proxy's fault (and un-pause forwarding);
        subsequent connections pass through clean. ``reset_active=True``
        also drops connections established while faulted — a blackholed
        socket a client is still waiting on does NOT recover by itself."""
        for proxy in self.proxies:
            proxy.fault = None
            proxy.pause_forwarding = False
        if reset_active:
            for proxy in self.proxies:
                proxy.reset_active()

    def pause(self) -> None:
        """Freeze every established flow (bytes buffer, nothing
        forwarded) without killing anything; :meth:`heal` releases."""
        for proxy in self.proxies:
            proxy.pause_forwarding = True

    def reset_active(self) -> None:
        """RST every currently-established connection, cell-wide."""
        for proxy in self.proxies:
            proxy.reset_active()

    def stats(self) -> Dict[str, int]:
        """Aggregated accept/fault counters across the cell's proxies."""
        out = {"connections": 0, "faulted": 0}
        for proxy in self.proxies:
            for key in out:
                out[key] += proxy.stats.get(key, 0)
        return out
