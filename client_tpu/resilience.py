"""Transport-agnostic resilience policies for the four client frontends.

The reference client leaves failure handling to the caller: a transient
connection reset, a slow-starting server, or a mid-stream disconnect all
surface as a raw ``InferenceServerException`` with no recovery path. This
module is the shared policy engine behind
``InferenceServerClientBase.configure_resilience``:

- :class:`RetryPolicy` — bounded retries with exponential backoff and full
  jitter, per-attempt and total deadline budgets, and a fault-domain gate
  that distinguishes *connect* failures (the request provably never reached
  the server — always safe to retry) from *transient* in-flight failures
  (reset / 503 / UNAVAILABLE — safe only for idempotent requests) from
  *fatal* errors (data corruption, protocol violations — never retried).
- :class:`CircuitBreaker` — closed → open → half-open with a sliding
  failure-rate window; an open circuit fast-fails with
  :class:`CircuitOpenError` instead of queueing doomed work (load shedding).
- :class:`ResiliencePolicy` — composes the two and runs an operation under
  them, sync (``execute``) or asyncio (``execute_async``).
- :class:`StreamReconnected` — the typed event a reconnecting GRPC stream
  delivers through its callback after transparently re-establishing the
  bidi call. Non-idempotent (sequence) requests are never silently
  re-sent; their ids arrive in ``abandoned_request_ids`` instead.

Classification is name-based over the exception cause chain plus the typed
exception's status, so the engine stays free of urllib3/aiohttp/grpc
imports and one policy object serves all four transports.
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence

from . import flight as _flight
from .utils import InferenceServerException

__all__ = [
    "CONNECT",
    "TRANSIENT",
    "TIMEOUT",
    "FATAL",
    "SHED",
    "INVALID",
    "AttemptBudget",
    "CircuitBreaker",
    "CircuitOpenError",
    "ResiliencePolicy",
    "ResilienceStats",
    "RetryPolicy",
    "RetryableStatusError",
    "StreamReconnected",
    "classify_fault",
]

# -- fault domains -----------------------------------------------------------
CONNECT = "connect"      # never reached the server: always safe to retry
TRANSIENT = "transient"  # may have reached the server: retry iff idempotent
TIMEOUT = "timeout"      # budget spent in flight: retry iff opted in + idempotent
FATAL = "fatal"          # corruption / protocol / application error: never retry
SHED = "shed"            # admission control rejected it client-side: never sent,
#                          never retried, and NOT a breaker/ejection signal —
#                          accounting counts it as shed, not error
INVALID = "invalid"      # the endpoint ANSWERED, but the answer failed contract
#                          validation (integrity.IntegrityError): never retried
#                          on the same endpoint, safe to fail over iff
#                          idempotent, counted into the pool's quarantine window

# client_tpu.admission.AdmissionRejected carries this status; matching on
# the status string keeps this module free of an admission import
_ADMISSION_REJECTED_STATUS = "ADMISSION_REJECTED"

# client_tpu.integrity.IntegrityError carries this status; same pattern —
# no integrity import here
_INTEGRITY_VIOLATION_STATUS = "INTEGRITY_VIOLATION"

# Exception type names (checked across the __cause__/__context__ chain, and
# across each exception's MRO) that mark a request as never-sent.
_CONNECT_TYPE_NAMES = frozenset({
    "NewConnectionError",       # urllib3: refused / DNS
    "ConnectTimeoutError",      # urllib3: SYNs dropped — equally never-sent
    "ClientConnectorError",     # aiohttp: refused / DNS
    "ConnectionRefusedError",
    "gaierror",
})

# In-flight transport deaths: the bytes may or may not have been processed.
_TRANSIENT_TYPE_NAMES = frozenset({
    "ProtocolError",            # urllib3 mid-body death
    "ConnectionResetError",
    "BrokenPipeError",
    "ConnectionAbortedError",
    "RemoteDisconnected",
    "IncompleteRead",
    "ServerDisconnectedError",  # aiohttp
    "ClientOSError",            # aiohttp
    "ClientPayloadError",       # aiohttp truncated body
})

_TIMEOUT_TYPE_NAMES = frozenset({
    "TimeoutError",
    "ReadTimeoutError",
    "ServerTimeoutError",
})

# HTTP statuses where the server (or an intermediary) explicitly shed the
# request; KServe/Triton semantics make these re-issuable.
RETRYABLE_HTTP_STATUSES = frozenset({"408", "429", "502", "503", "504"})
_TIMEOUT_HTTP_STATUSES = frozenset({"499"})

_TRANSIENT_GRPC_STATUSES = frozenset({
    "StatusCode.UNAVAILABLE",
    "StatusCode.RESOURCE_EXHAUSTED",
})
_TIMEOUT_GRPC_STATUSES = frozenset({"StatusCode.DEADLINE_EXCEEDED"})

_CONNECT_DETAIL_MARKERS = (
    "failed to connect",
    "connection refused",
    "connect failed",
    "name resolution",
    "dns resolution",
)


class CircuitOpenError(InferenceServerException):
    """Fast-fail raised while a circuit breaker is open (load shedding)."""

    def __init__(self, msg: str = "circuit breaker is open; request fast-failed",
                 retry_after_s: Optional[float] = None):
        super().__init__(msg, status="CIRCUIT_OPEN")
        self.retry_after_s = retry_after_s


class RetryableStatusError(InferenceServerException):
    """Internal marker: an HTTP response whose status is worth retrying.

    The HTTP frontends raise it *inside* a resilient attempt so the engine
    re-issues the request, then unwrap ``response`` at the boundary when
    attempts are exhausted — callers keep seeing a plain response + the
    usual ``raise_if_error`` path, never this type.
    """

    def __init__(self, status: int, response: Any):
        super().__init__(f"retryable HTTP status {status}", status=str(status))
        self.response = response


def _chain(exc: BaseException) -> List[BaseException]:
    """The exception plus its cause/context chain (cycle-safe)."""
    out: List[BaseException] = []
    seen = set()
    cur: Optional[BaseException] = exc
    while cur is not None and id(cur) not in seen:
        seen.add(id(cur))
        out.append(cur)
        cur = cur.__cause__ if cur.__cause__ is not None else cur.__context__
    return out


def _type_names(exc: BaseException) -> List[str]:
    return [c.__name__ for c in type(exc).__mro__]


def classify_fault(exc: BaseException) -> str:
    """Map an exception (typically the clients' typed exception, with the
    transport error as its ``__cause__``) to a fault domain."""
    if isinstance(exc, CircuitOpenError):
        return FATAL  # retrying inside an open circuit defeats the breaker
    if (isinstance(exc, InferenceServerException)
            and exc.status() == _ADMISSION_REJECTED_STATUS):
        # admission control shed it before anything touched the wire:
        # never retried (retries_domain: unknown domain -> False), never
        # a breaker outcome (see _record), counted as shed by harnesses
        return SHED
    chain = _chain(exc)
    for e in chain:
        if (isinstance(e, InferenceServerException)
                and e.status() == _INTEGRITY_VIOLATION_STATUS):
            # the transport worked end to end and the server answered —
            # wrongly. Same-endpoint retry would re-trust a liar
            # (retries_domain: unknown domain -> False); the pool fails
            # over idempotent requests and counts it toward quarantine.
            return INVALID
    names: List[str] = []
    for e in chain:
        names.extend(_type_names(e))
    name_set = set(names)
    if name_set & _CONNECT_TYPE_NAMES:
        return CONNECT
    status = None
    message = ""
    for e in chain:
        if isinstance(e, InferenceServerException):
            status = status if status is not None else e.status()
            message = message or (e.message() or "")
    if status is not None:
        if status in RETRYABLE_HTTP_STATUSES or status in _TRANSIENT_GRPC_STATUSES:
            low = message.lower()
            if any(marker in low for marker in _CONNECT_DETAIL_MARKERS):
                return CONNECT
            return TRANSIENT
        if status in _TIMEOUT_HTTP_STATUSES or status in _TIMEOUT_GRPC_STATUSES:
            return TIMEOUT
    if name_set & _TRANSIENT_TYPE_NAMES:
        return TRANSIENT
    if name_set & _TIMEOUT_TYPE_NAMES:
        return TIMEOUT
    return FATAL


class RetryPolicy:
    """Exponential backoff with full jitter, bounded by attempts + deadlines.

    ``max_attempts`` counts the first try: ``max_attempts=1`` disables
    retries. ``total_deadline_s`` bounds the whole resilient call (attempts
    plus backoff sleeps) when the caller supplies no explicit per-request
    timeout; an explicit timeout always wins. ``per_attempt_timeout_s`` is
    advisory for transports that accept a per-attempt socket timeout.
    """

    def __init__(
        self,
        max_attempts: int = 4,
        initial_backoff_s: float = 0.05,
        max_backoff_s: float = 2.0,
        backoff_multiplier: float = 2.0,
        jitter: bool = True,
        per_attempt_timeout_s: Optional[float] = None,
        total_deadline_s: Optional[float] = None,
        retry_connect: bool = True,
        retry_transient: bool = True,
        retry_timeouts: bool = False,
        rng: Optional[random.Random] = None,
    ):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if initial_backoff_s < 0 or max_backoff_s < 0:
            raise ValueError("backoff must be >= 0")
        self.max_attempts = max_attempts
        self.initial_backoff_s = initial_backoff_s
        self.max_backoff_s = max_backoff_s
        self.backoff_multiplier = backoff_multiplier
        self.jitter = jitter
        self.per_attempt_timeout_s = per_attempt_timeout_s
        self.total_deadline_s = total_deadline_s
        self.retry_connect = retry_connect
        self.retry_transient = retry_transient
        self.retry_timeouts = retry_timeouts
        self._rng = rng or random.Random()

    def backoff_s(self, attempt: int) -> float:
        """Backoff before re-attempt number ``attempt+1`` (attempt is 0-based)."""
        base = min(
            self.initial_backoff_s * (self.backoff_multiplier ** attempt),
            self.max_backoff_s,
        )
        if not self.jitter:
            return base
        return self._rng.uniform(0.0, base)  # full jitter (AWS-style)

    def retries_domain(self, domain: str, idempotent: bool) -> bool:
        if domain == CONNECT:
            return self.retry_connect
        if domain == TRANSIENT:
            return self.retry_transient and idempotent
        if domain == TIMEOUT:
            return self.retry_timeouts and idempotent
        return False


class CircuitBreaker:
    """Sliding-window failure-rate circuit breaker (thread-safe).

    closed: all calls pass; outcomes fill a window of the last
    ``window`` transport-level results. Once at least ``min_calls`` are
    recorded and the failure rate reaches ``failure_threshold``, the
    circuit opens. open: calls fast-fail with :class:`CircuitOpenError`
    until ``recovery_time_s`` elapses. half-open: up to
    ``half_open_max_probes`` calls are let through; a success closes the
    circuit (window cleared), a failure re-opens it.

    Only transport-level failures (connect/transient/timeout domains)
    count against the breaker; application errors (4xx, corruption) prove
    the transport delivered the request and count as successes — so a 4xx
    answer to a half-open probe closes the circuit instead of wedging it.
    """

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(
        self,
        failure_threshold: float = 0.5,
        window: int = 16,
        min_calls: int = 8,
        recovery_time_s: float = 5.0,
        half_open_max_probes: int = 1,
        clock: Callable[[], float] = time.monotonic,
    ):
        if not 0.0 < failure_threshold <= 1.0:
            raise ValueError("failure_threshold must be in (0, 1]")
        if window < 1 or min_calls < 1:
            raise ValueError("window and min_calls must be >= 1")
        self.failure_threshold = failure_threshold
        self.window = window
        self.min_calls = min_calls
        self.recovery_time_s = recovery_time_s
        self.half_open_max_probes = half_open_max_probes
        # observability hook: called with the NEW state name after every
        # transition, outside the breaker lock (must be fast + non-raising;
        # observe.Telemetry.attach wires it to a transition counter)
        self.on_transition: Optional[Callable[[str], None]] = None
        self._clock = clock
        self._lock = threading.Lock()
        self._outcomes: deque = deque(maxlen=window)
        self._state = self.CLOSED
        self._opened_at = 0.0
        self._probes_in_flight = 0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _notify(self, state: Optional[str]) -> None:
        if state is None:
            return
        # the transition lands on the flight timeline of whichever request
        # caused it (the allow()/record() caller) — a breaker flip is a
        # per-request causal fact, not only a fleet counter
        _flight.note("breaker", "transition", state=state)
        if self.on_transition is None:
            return
        try:
            self.on_transition(state)
        except Exception:
            pass  # an observer must never break the data path

    def allow(self) -> None:
        """Admit one call or raise :class:`CircuitOpenError`."""
        transition = None
        try:
            with self._lock:
                if self._state == self.CLOSED:
                    return
                now = self._clock()
                if self._state == self.OPEN:
                    remaining = self._opened_at + self.recovery_time_s - now
                    if remaining > 0:
                        raise CircuitOpenError(
                            f"circuit breaker open; retry in {remaining:.3f}s",
                            retry_after_s=remaining,
                        )
                    self._state = self.HALF_OPEN
                    self._probes_in_flight = 0
                    transition = self.HALF_OPEN
                # HALF_OPEN: admit a bounded number of probes
                if self._probes_in_flight >= self.half_open_max_probes:
                    raise CircuitOpenError(
                        "circuit breaker half-open; probe already in flight",
                        retry_after_s=self.recovery_time_s,
                    )
                self._probes_in_flight += 1
        finally:
            self._notify(transition)

    def record(self, ok: bool) -> None:
        transition = None
        with self._lock:
            if self._state == self.HALF_OPEN:
                self._probes_in_flight = max(0, self._probes_in_flight - 1)
                if ok:
                    self._state = self.CLOSED
                    self._outcomes.clear()
                    transition = self.CLOSED
                else:
                    self._state = self.OPEN
                    self._opened_at = self._clock()
                    transition = self.OPEN
            else:
                self._outcomes.append(ok)
                if (self._state == self.CLOSED
                        and len(self._outcomes) >= self.min_calls):
                    failures = sum(1 for o in self._outcomes if not o)
                    if failures / len(self._outcomes) >= self.failure_threshold:
                        self._state = self.OPEN
                        self._opened_at = self._clock()
                        transition = self.OPEN
        self._notify(transition)

    def would_admit(self) -> bool:
        """Non-mutating peek: would :meth:`allow` admit a call right now?

        Unlike ``allow`` this takes no probe slot and performs no state
        transition, so selection layers (the endpoint pool) can skip an
        endpoint whose breaker would fast-fail without consuming the
        half-open probe budget. Inherently racy under concurrency — the
        admitting ``allow`` remains the authority and callers must still
        handle :class:`CircuitOpenError`."""
        with self._lock:
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN:
                # an elapsed recovery window means allow() would half-open
                # and admit the first probe
                return self._clock() >= self._opened_at + self.recovery_time_s
            return self._probes_in_flight < self.half_open_max_probes

    def abort_probe(self) -> None:
        """Release an admitted probe slot without recording an outcome
        (the attempt was interrupted, e.g. cancellation/KeyboardInterrupt —
        half-open has no time-based escape, so a leaked slot wedges the
        breaker forever)."""
        with self._lock:
            if self._state == self.HALF_OPEN:
                self._probes_in_flight = max(0, self._probes_in_flight - 1)

    def reset(self) -> None:
        with self._lock:
            changed = self._state != self.CLOSED
            self._state = self.CLOSED
            self._outcomes.clear()
            self._probes_in_flight = 0
        if changed:
            self._notify(self.CLOSED)


class ResilienceStats:
    """Cumulative counters for one policy object (thread-safe writes,
    lock-free reads)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.calls = 0
        self.attempts = 0
        self.retries = 0
        self.fast_fails = 0

    def _bump(self, calls=0, attempts=0, retries=0, fast_fails=0) -> None:
        with self._lock:
            self.calls += calls
            self.attempts += attempts
            self.retries += retries
            self.fast_fails += fast_fails

    def as_dict(self) -> Dict[str, int]:
        # lock-free: each counter is one int slot only ever mutated under
        # _bump's lock, so a read sees a valid value; the four reads may be
        # an increment apart, which a metrics scrape tolerates — taking the
        # lock here would put scrapers on the data path's critical section
        return {
            "calls": self.calls,
            "attempts": self.attempts,
            "retries": self.retries,
            "fast_fails": self.fast_fails,
        }


class StreamReconnected:
    """Delivered through a reconnecting stream's callback (as the result,
    with ``error=None``) after the bidi call was re-established.

    ``resent_request_ids``: idempotent requests that were in flight on the
    dead stream and were transparently re-sent on the new one.
    ``abandoned_request_ids``: non-idempotent (sequence) requests that were
    in flight — these are NEVER silently re-sent; the application owns
    re-driving its sequence state.
    """

    __slots__ = ("attempt", "resent_request_ids", "abandoned_request_ids", "cause")

    def __init__(self, attempt: int, resent_request_ids: Sequence[str],
                 abandoned_request_ids: Sequence[str],
                 cause: Optional[Exception] = None):
        self.attempt = attempt
        self.resent_request_ids = list(resent_request_ids)
        self.abandoned_request_ids = list(abandoned_request_ids)
        self.cause = cause

    def __repr__(self) -> str:
        return (
            f"StreamReconnected(attempt={self.attempt}, "
            f"resent={self.resent_request_ids}, "
            f"abandoned={self.abandoned_request_ids})"
        )


class AttemptBudget:
    """Shared deadline arithmetic for the frontends' retrying request
    wrappers: derives the total budget (the caller's explicit timeout,
    else the retry policy's total deadline — which must bound in-flight
    attempts too, not only backoff sleeps) and clamps every re-attempt to
    the REMAINING budget and the policy's per-attempt timeout, so a
    re-attempt never gets a fresh full timeout."""

    __slots__ = ("per_attempt_s", "deadline")

    def __init__(self, policy: Optional["ResiliencePolicy"],
                 timeout_s: Optional[float] = None):
        budget = timeout_s
        self.per_attempt_s: Optional[float] = None
        if policy is not None and policy.retry is not None:
            self.per_attempt_s = policy.retry.per_attempt_timeout_s
            if budget is None:
                budget = policy.retry.total_deadline_s
        self.deadline = (
            time.monotonic() + budget if budget is not None else None)

    def attempt_timeout_s(self, status: str = "499") -> Optional[float]:
        """Timeout for the next attempt: the remaining total budget clamped
        to the per-attempt timeout, or None when both are unbounded. Raises
        a typed Deadline Exceeded (with the transport's ``status`` code)
        when the budget is already spent, so the engine never launches a
        doomed attempt."""
        remaining = None
        if self.deadline is not None:
            remaining = self.deadline - time.monotonic()
            if remaining <= 0:
                raise InferenceServerException(
                    "Deadline Exceeded", status=status)
        if self.per_attempt_s is not None:
            remaining = (self.per_attempt_s if remaining is None
                         else min(remaining, self.per_attempt_s))
        return remaining


class ResiliencePolicy:
    """Retry + circuit-breaker composition with sync and asyncio engines.

    One policy may be shared across clients; the breaker window then
    reflects the whole process' view of the endpoint (that is the point).
    Per-request overrides go through ``execute(..., retry=...)`` or the
    clients' ``resilience=`` keyword.
    """

    def __init__(
        self,
        retry: Optional[RetryPolicy] = None,
        breaker: Optional[CircuitBreaker] = None,
        classify: Callable[[BaseException], str] = classify_fault,
        retry_http_statuses: bool = True,
    ):
        self.retry = retry
        self.breaker = breaker
        self.classify = classify
        # when True the HTTP frontends convert 408/429/502/503/504 responses
        # into retryable attempts (unwrapped back to plain responses at the
        # boundary if attempts run out)
        self.retry_http_statuses = retry_http_statuses
        self.stats = ResilienceStats()
        # observability hook (duck-typed; see observe.Telemetry.attach):
        # on_retry(attempt, exc, delay_s) / on_fast_fail() called alongside
        # the stats counters — must be fast and non-raising
        self.observer = None

    # -- decision core (shared by both engines) -----------------------------
    @staticmethod
    def _deadline(timeout_s: Optional[float],
                  retry: Optional[RetryPolicy]) -> Optional[float]:
        budget = timeout_s
        if budget is None and retry is not None:
            budget = retry.total_deadline_s
        return time.monotonic() + budget if budget is not None else None

    def _retry_delay(
        self,
        exc: BaseException,
        attempt: int,
        idempotent: bool,
        deadline: Optional[float],
        retry: Optional[RetryPolicy],
    ) -> Optional[float]:
        """Backoff before the next attempt, or None when ``exc`` is final."""
        if retry is None or attempt + 1 >= retry.max_attempts:
            return None
        domain = self.classify(exc)
        if not retry.retries_domain(domain, idempotent):
            return None
        delay = retry.backoff_s(attempt)
        if deadline is not None and time.monotonic() + delay >= deadline:
            return None
        return delay

    def _record(self, exc: Optional[BaseException]) -> None:
        breaker = self.breaker
        if breaker is None:
            return
        if exc is None:
            breaker.record(True)
        elif isinstance(exc, CircuitOpenError):
            # a (nested) fast-fail never touched the endpoint, so there is
            # no outcome to record — but if op() raised it while OUR breaker
            # was half-open, the admitted probe slot must be released or the
            # breaker wedges (half-open has no time-based escape)
            breaker.abort_probe()
        elif self.classify(exc) == SHED:
            # a client-local admission rejection never touched the
            # endpoint: no outcome to record, but a half-open probe slot
            # taken by this attempt must be released (same rule as a
            # nested CircuitOpenError)
            breaker.abort_probe()
        elif self.classify(exc) in (CONNECT, TRANSIENT, TIMEOUT):
            breaker.record(False)
        else:
            # FATAL (application) errors prove the transport worked — the
            # request reached the server and was answered — so they count
            # as breaker successes; anything else would leak the half-open
            # probe slot and wedge the breaker on a 4xx probe response
            breaker.record(True)

    # -- engines -------------------------------------------------------------
    def execute(
        self,
        op: Callable[[], Any],
        *,
        idempotent: bool = True,
        timeout_s: Optional[float] = None,
        retry: Optional[RetryPolicy] = None,
        on_retry: Optional[Callable[[int, BaseException, float], None]] = None,
        sleep: Callable[[float], None] = time.sleep,
    ) -> Any:
        """Run ``op()`` under the policy; returns its result or raises the
        final error. ``retry`` overrides the policy's RetryPolicy for this
        call (per-request hook)."""
        active_retry = retry if retry is not None else self.retry
        deadline = self._deadline(timeout_s, active_retry)
        self.stats._bump(calls=1)
        attempt = 0
        while True:
            if self.breaker is not None:
                try:
                    self.breaker.allow()
                except CircuitOpenError:
                    self.stats._bump(fast_fails=1)
                    _flight.note("breaker", "fast_fail")
                    if self.observer is not None:
                        try:
                            self.observer.on_fast_fail()
                        except Exception:
                            pass
                    raise
            self.stats._bump(attempts=1)
            try:
                result = op()
            except Exception as exc:
                self._record(exc)
                delay = self._retry_delay(
                    exc, attempt, idempotent, deadline, active_retry)
                if delay is None:
                    raise
                if on_retry is not None:
                    on_retry(attempt, exc, delay)
                self.stats._bump(retries=1)
                _flight.note("retry", "attempt", n=attempt + 1,
                             delay_ms=round(delay * 1e3, 3),
                             error=type(exc).__name__)
                if self.observer is not None:
                    try:
                        self.observer.on_retry(attempt, exc, delay)
                    except Exception:
                        pass
                sleep(delay)
                attempt += 1
                continue
            except BaseException:
                # KeyboardInterrupt/SystemExit: no outcome to record, but a
                # half-open probe slot must be released or the breaker wedges
                if self.breaker is not None:
                    self.breaker.abort_probe()
                raise
            self._record(None)
            return result

    async def execute_async(
        self,
        op: Callable[[], Any],
        *,
        idempotent: bool = True,
        timeout_s: Optional[float] = None,
        retry: Optional[RetryPolicy] = None,
        on_retry: Optional[Callable[[int, BaseException, float], None]] = None,
    ) -> Any:
        """Asyncio twin of :meth:`execute`; ``op`` is a coroutine function."""
        import asyncio

        active_retry = retry if retry is not None else self.retry
        deadline = self._deadline(timeout_s, active_retry)
        self.stats._bump(calls=1)
        attempt = 0
        while True:
            if self.breaker is not None:
                try:
                    self.breaker.allow()
                except CircuitOpenError:
                    self.stats._bump(fast_fails=1)
                    _flight.note("breaker", "fast_fail")
                    if self.observer is not None:
                        try:
                            self.observer.on_fast_fail()
                        except Exception:
                            pass
                    raise
            self.stats._bump(attempts=1)
            try:
                result = await op()
            except Exception as exc:
                self._record(exc)
                delay = self._retry_delay(
                    exc, attempt, idempotent, deadline, active_retry)
                if delay is None:
                    raise
                if on_retry is not None:
                    on_retry(attempt, exc, delay)
                self.stats._bump(retries=1)
                _flight.note("retry", "attempt", n=attempt + 1,
                             delay_ms=round(delay * 1e3, 3),
                             error=type(exc).__name__)
                if self.observer is not None:
                    try:
                        self.observer.on_retry(attempt, exc, delay)
                    except Exception:
                        pass
                await asyncio.sleep(delay)
                attempt += 1
                continue
            except BaseException:
                # asyncio.CancelledError is a BaseException: a cancelled
                # probe must release its half-open slot
                if self.breaker is not None:
                    self.breaker.abort_probe()
                raise
            self._record(None)
            return result


def connect_only_policy(max_retries: int) -> Optional[ResiliencePolicy]:
    """The legacy ``max_retries`` semantics as a policy: re-attempt only
    connect-class failures (request provably never sent), deterministic
    linear-ish backoff, no breaker. None when retries are disabled."""
    if max_retries <= 0:
        return None
    return ResiliencePolicy(
        retry=RetryPolicy(
            max_attempts=max_retries + 1,
            initial_backoff_s=0.05,
            max_backoff_s=0.5,
            jitter=False,
            retry_connect=True,
            retry_transient=False,
            retry_timeouts=False,
        ),
        retry_http_statuses=False,
    )
