"""Replayable workload traces: a versioned JSONL format + seeded generators.

Production traffic is bursty, heavy-tailed and mixed — unary infers,
SSE/decoupled generation streams and stateful sequences interleave on one
client. The closed/open-loop sweeps in ``client_tpu.perf`` can't answer
"what QPS can this fleet serve inside SLO?" for that shape, so this module
gives the perf harness something replayable:

- **Format** (:class:`TraceRecord`, :func:`dump_trace` / :func:`load_trace`):
  one JSON object per line. The first line is a ``type: "header"`` record
  carrying the format version and generator provenance; every following
  line is a ``type: "request"`` record with an arrival offset (``at_s``,
  seconds from replay start), a ``kind`` (``unary`` | ``generate_stream``
  | ``sequence`` | ``sharded`` | ``prefill_decode``), the target
  model/version, and
  kind-specific payload sizing — tensor ``shapes``/``dtypes`` for unary,
  sequence and sharded records, ``prompt_tokens``/``output_tokens`` for
  streams. Sequence records carry ``(seq_group, seq_index, seq_len)`` so
  the replayer can pin each group to one replica (the pool's affinity
  rules) and issue its steps in order. ``sharded`` records (format v2,
  stamped per record so v1 loaders skip-and-count them) are logical
  scatter-gather requests replayed through ``perf.py --shard-layout``
  (``client_tpu.shard``). Records may carry a ``tenant`` attribution
  (format v4, stamped per record) that the replayer threads through the
  client's admission/cache/batch layers as the multi-tenant QoS
  dimension — it never reaches the wire. ``prefill_decode`` records
  (format v5, stamped per record so v4 loaders skip-and-count them) are
  disaggregated prefill/decode sessions — ``prompt_tokens`` /
  ``output_tokens`` sizing plus optional ``prefill_role`` /
  ``decode_role`` hints — replayed through
  ``client_tpu.disagg.DisaggClient`` (``perf.py --roles``).

- **Versioning**: the header's ``version`` is the format version; a
  *record* may carry its own ``v`` — records (and whole traces) from a
  NEWER format are skipped, not fatal (forward compatibility), and the
  loader reports how many it skipped. Malformed lines are fatal with the
  1-based line number (:class:`TraceParseError`).

- **Generators** (:func:`poisson_burst`, :func:`heavy_tail`,
  :func:`mixed`, :func:`multi_tenant`, or :func:`generate` from a
  ``name:k=v,...`` spec string):
  each is a pure function of ``(seed, duration, params)`` over ONE
  ``numpy.random.Generator`` — the same seed and spec always produce a
  byte-identical trace (see :func:`dumps_trace`), so traces are
  reproducible without being committed.

The replay engine lives in ``client_tpu.perf`` (``--trace`` /
``--trace-gen``); the capacity-search driver in ``tools/bench_capacity.py``.
See docs/capacity.md.
"""

from __future__ import annotations

import dataclasses
import io
import json
import math
from typing import Any, Dict, IO, Iterable, List, Optional, Tuple, Union

import numpy as np

# what THIS parser understands; headers are written at the BASE version so
# a v1 reader still loads the v1-compatible records of a mixed trace, and
# only records carrying newer-versioned semantics stamp their own ``v``
# (the PR 8 forward-compat rule: skip-and-count, never fatal)
TRACE_VERSION = 6
BASE_VERSION = 1
# record kinds introduced after the base format stamp their records with
# the version that introduced them
_KIND_VERSIONS = {"sharded": 2, "prefill_decode": 5, "pipeline": 6}
# records carrying a zipfian ``content_key`` (the hot-key workload knob)
# stamp v=3: a v2 loader skips exactly these, counted, and keeps the rest
_CONTENT_KEY_VERSION = 3
# records carrying a ``tenant`` attribution (the multi-tenant QoS knob)
# stamp v=4 — same rule: an older loader skips exactly the tenant-stamped
# records (counted), and tenantless specs keep producing byte-identical
# traces (no tenant field, no version stamp)
_TENANT_VERSION = 4

KINDS = ("unary", "generate_stream", "sequence", "sharded",
         "prefill_decode", "pipeline")

# default tensor layouts per well-known zoo model, so generator specs can
# name a model without restating its wire contract
_DEFAULT_LAYOUTS: Dict[str, Tuple[Dict[str, List[int]], Dict[str, str]]] = {
    "simple": ({"INPUT0": [1, 16], "INPUT1": [1, 16]},
               {"INPUT0": "INT32", "INPUT1": "INT32"}),
    "batched_matmul": ({"X": [1, 64]}, {"X": "FP32"}),
    "simple_sequence": ({"INPUT": [1, 1]}, {"INPUT": "INT32"}),
    # stateless batched prompt scoring (client_tpu/shard.py's batch-axis
    # scatter-gather targets); replay tokens stay inside the VOCAB
    "decoder_lm_prefill": ({"TOKENS": [4, 8]}, {"TOKENS": "INT32"}),
    "decoder_lm_tp_prefill": ({"TOKENS": [4, 8]}, {"TOKENS": "INT32"}),
    # the pipeline chain's feed layout (client_tpu/pipeline.py): the
    # record's model names the PIPELINE, shapes/dtypes its declared feeds
    "chain": ({"RAW": [1, 16]}, {"RAW": "INT32"}),
}


class TraceParseError(ValueError):
    """A malformed trace line; ``line`` is 1-based."""

    def __init__(self, line: int, message: str):
        super().__init__(f"line {line}: {message}")
        self.line = line


@dataclasses.dataclass(frozen=True)
class TraceRecord:
    """One scheduled request. ``at_s`` is the arrival offset from replay
    start; replaying at speed ``s`` schedules it at ``at_s / s``."""

    at_s: float
    kind: str
    model: str
    version: str = ""
    # unary / sequence / sharded payload sizing
    shapes: Optional[Dict[str, List[int]]] = None
    dtypes: Optional[Dict[str, str]] = None
    # generate_stream payload sizing
    prompt_tokens: Optional[int] = None
    output_tokens: Optional[int] = None
    # sequence grouping: step seq_index of seq_len in group seq_group
    seq_group: Optional[int] = None
    seq_index: Optional[int] = None
    seq_len: Optional[int] = None
    # sharded records: the generator's declared fan-out (informational —
    # the replayer's --shard-layout decides the real endpoints/axes)
    shards: Optional[int] = None
    # hot-key workloads (format v3): the zipf-drawn content identity —
    # records with equal keys replay BYTE-IDENTICAL payloads (the
    # replayer synthesizes per-key deterministic tensors/prompts), so the
    # client-side cache/singleflight layer has real hot keys to collapse;
    # it also doubles as the session key for ``routing="affinity"``
    content_key: Optional[int] = None
    # multi-tenant workloads (format v4): the requesting tenant — the
    # replayer threads it as ``infer(tenant=...)`` so admission quotas,
    # weighted-fair drain and cache partitions see the same tenant mix
    # the generator declared. None (the default) stamps nothing.
    tenant: Optional[str] = None
    # prefill_decode records (format v5): role hints for the replayer's
    # DisaggClient — which pool role serves each leg. None lets the
    # replayer's own defaults ("prefill"/"decode") apply.
    prefill_role: Optional[str] = None
    decode_role: Optional[str] = None

    def to_obj(self) -> Dict[str, Any]:
        obj: Dict[str, Any] = {
            "type": "request",
            "at_s": round(float(self.at_s), 6),
            "kind": self.kind,
            "model": self.model,
        }
        if self.version:
            obj["model_version"] = self.version
        if self.shapes is not None:
            obj["shapes"] = {k: list(v) for k, v in self.shapes.items()}
            obj["dtypes"] = dict(self.dtypes or {})
        if self.kind in ("generate_stream", "prefill_decode"):
            obj["prompt_tokens"] = int(self.prompt_tokens)
            obj["output_tokens"] = int(self.output_tokens)
        if self.kind == "prefill_decode":
            if self.prefill_role is not None:
                obj["prefill_role"] = str(self.prefill_role)
            if self.decode_role is not None:
                obj["decode_role"] = str(self.decode_role)
        if self.kind == "sequence":
            obj["seq_group"] = int(self.seq_group)
            obj["seq_index"] = int(self.seq_index)
            obj["seq_len"] = int(self.seq_len)
        if self.kind == "sharded" and self.shards is not None:
            obj["shards"] = int(self.shards)
        v = _KIND_VERSIONS.get(self.kind, BASE_VERSION)
        if self.content_key is not None:
            obj["content_key"] = int(self.content_key)
            v = max(v, _CONTENT_KEY_VERSION)
        if self.tenant is not None:
            obj["tenant"] = str(self.tenant)
            v = max(v, _TENANT_VERSION)
        if v > BASE_VERSION:
            # newer-versioned records stamp their own version so an older
            # reader skips exactly these (counted) and keeps the rest
            obj["v"] = v
        return obj

    @classmethod
    def from_obj(cls, obj: Dict[str, Any], line: int) -> "TraceRecord":
        kind = obj.get("kind")
        if kind not in KINDS:
            raise TraceParseError(line, f"unknown kind {kind!r}")
        try:
            at_s = float(obj["at_s"])
        except (KeyError, TypeError, ValueError):
            raise TraceParseError(line, "missing/non-numeric at_s") from None
        if at_s < 0.0 or not math.isfinite(at_s):
            raise TraceParseError(line, f"at_s out of range: {at_s!r}")
        model = obj.get("model")
        if not model or not isinstance(model, str):
            raise TraceParseError(line, "missing model")
        kwargs: Dict[str, Any] = {
            "at_s": round(at_s, 6), "kind": kind, "model": model,
            "version": str(obj.get("model_version", "")),
        }
        if kind in ("unary", "sequence", "sharded", "pipeline") \
                and "shapes" not in obj:
            raise TraceParseError(
                line, f"{kind} requires shapes/dtypes")
        if "shapes" in obj:
            shapes = obj["shapes"]
            dtypes = obj.get("dtypes", {})
            if not isinstance(shapes, dict) or not isinstance(dtypes, dict):
                raise TraceParseError(line, "shapes/dtypes must be objects")
            try:
                kwargs["shapes"] = {
                    str(k): [int(d) for d in v] for k, v in shapes.items()}
            except (TypeError, ValueError):
                raise TraceParseError(
                    line, "shapes must map name -> [int, ...]") from None
            kwargs["dtypes"] = {str(k): str(v) for k, v in dtypes.items()}
            missing = set(kwargs["shapes"]) - set(kwargs["dtypes"])
            if missing:
                raise TraceParseError(
                    line, f"shapes without dtypes: {sorted(missing)}")
        if kind in ("generate_stream", "prefill_decode"):
            try:
                kwargs["prompt_tokens"] = int(obj["prompt_tokens"])
                kwargs["output_tokens"] = int(obj["output_tokens"])
            except (KeyError, TypeError, ValueError):
                raise TraceParseError(
                    line, f"{kind} requires integer "
                    "prompt_tokens/output_tokens") from None
            if kwargs["prompt_tokens"] < 1 or kwargs["output_tokens"] < 1:
                raise TraceParseError(line, "token counts must be >= 1")
        if kind == "prefill_decode":
            for field in ("prefill_role", "decode_role"):
                if field in obj:
                    role = obj[field]
                    if not isinstance(role, str) or not role:
                        raise TraceParseError(
                            line, f"{field} must be a non-empty string")
                    kwargs[field] = role
        if kind == "sequence":
            try:
                kwargs["seq_group"] = int(obj["seq_group"])
                kwargs["seq_index"] = int(obj["seq_index"])
                kwargs["seq_len"] = int(obj["seq_len"])
            except (KeyError, TypeError, ValueError):
                raise TraceParseError(
                    line, "sequence requires integer "
                    "seq_group/seq_index/seq_len") from None
            if not 0 <= kwargs["seq_index"] < kwargs["seq_len"]:
                raise TraceParseError(
                    line, f"seq_index {kwargs['seq_index']} outside "
                    f"seq_len {kwargs['seq_len']}")
        if kind == "sharded" and "shards" in obj:
            try:
                kwargs["shards"] = int(obj["shards"])
            except (TypeError, ValueError):
                raise TraceParseError(
                    line, "shards must be an integer") from None
            if kwargs["shards"] < 1:
                raise TraceParseError(line, "shards must be >= 1")
        if "content_key" in obj:
            try:
                kwargs["content_key"] = int(obj["content_key"])
            except (TypeError, ValueError):
                raise TraceParseError(
                    line, "content_key must be an integer") from None
            if kwargs["content_key"] < 0:
                raise TraceParseError(line, "content_key must be >= 0")
        if "tenant" in obj:
            tenant = obj["tenant"]
            if not isinstance(tenant, str) or not tenant:
                raise TraceParseError(
                    line, "tenant must be a non-empty string")
            kwargs["tenant"] = tenant
        return cls(**kwargs)


@dataclasses.dataclass
class Trace:
    """A loaded trace: header metadata + chronologically sorted records.
    ``skipped`` counts newer-version records the loader passed over."""

    header: Dict[str, Any]
    records: List[TraceRecord]
    skipped: int = 0

    @property
    def duration_s(self) -> float:
        """Nominal duration: the header's declared span, else the last
        arrival offset."""
        declared = self.header.get("duration_s")
        if declared:
            return float(declared)
        return self.records[-1].at_s if self.records else 0.0

    def kind_counts(self) -> Dict[str, int]:
        counts = {k: 0 for k in KINDS}
        for rec in self.records:
            counts[rec.kind] += 1
        return counts


# -- serialization ------------------------------------------------------------
def _record_line(obj: Dict[str, Any]) -> str:
    # sort_keys + fixed separators: serialization is a pure function of the
    # record, so generator determinism carries through to bytes on disk
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


def dumps_trace(records: Iterable[TraceRecord],
                header: Optional[Dict[str, Any]] = None) -> str:
    """The trace as one JSONL string (header line first). Byte-identical
    for identical ``(records, header)`` — the determinism contract."""
    head = {"type": "header", "version": BASE_VERSION}
    head.update(header or {})
    records = list(records)
    head["records"] = len(records)
    lines = [_record_line(head)]
    lines.extend(_record_line(rec.to_obj()) for rec in records)
    return "\n".join(lines) + "\n"


def dump_trace(records: Iterable[TraceRecord],
               path_or_fp: Union[str, IO[str]],
               header: Optional[Dict[str, Any]] = None) -> None:
    text = dumps_trace(records, header)
    if hasattr(path_or_fp, "write"):
        path_or_fp.write(text)
    else:
        with open(path_or_fp, "w", encoding="utf-8") as fp:
            fp.write(text)


def loads_trace(text: str) -> Trace:
    return load_trace(io.StringIO(text))


def load_trace(path_or_fp: Union[str, IO[str]]) -> Trace:
    """Parse a JSONL trace. Malformed lines raise :class:`TraceParseError`
    with the 1-based line number; records (or a whole trace) stamped with
    a NEWER format version are skipped and counted, never fatal."""
    if hasattr(path_or_fp, "read"):
        fp = path_or_fp
        close = False
    else:
        fp = open(path_or_fp, "r", encoding="utf-8")
        close = True
    header: Dict[str, Any] = {"version": TRACE_VERSION}
    records: List[TraceRecord] = []
    skipped = 0
    try:
        for lineno, raw in enumerate(fp, start=1):
            line = raw.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError as e:
                raise TraceParseError(lineno, f"invalid JSON ({e.msg})") \
                    from None
            if not isinstance(obj, dict):
                raise TraceParseError(lineno, "record must be a JSON object")
            rtype = obj.get("type", "request")
            if rtype == "header":
                header = {k: v for k, v in obj.items() if k != "type"}
                continue
            # forward compatibility: a record from a newer format version
            # may carry fields with semantics this parser predates — skip
            # it (counted) instead of guessing
            v = obj.get("v", header.get("version", TRACE_VERSION))
            try:
                v = int(v)
            except (TypeError, ValueError):
                raise TraceParseError(lineno, f"non-integer version {v!r}") \
                    from None
            if v > TRACE_VERSION:
                skipped += 1
                continue
            if rtype != "request":
                skipped += 1  # unknown record types: same forward-compat rule
                continue
            records.append(TraceRecord.from_obj(obj, lineno))
    finally:
        if close:
            fp.close()
    records.sort(key=lambda r: r.at_s)
    return Trace(header=header, records=records, skipped=skipped)


# -- generators ---------------------------------------------------------------
def _modulated_rate(t: float, rate: float, burst_factor: float,
                    period_s: float, duty: float) -> float:
    """On/off modulated instantaneous rate with mean ``rate``: bursts at
    ``rate * burst_factor`` for ``duty`` of each period, with the off-phase
    rate chosen so the long-run mean stays ``rate`` (clamped at 0 when the
    burst alone exceeds the mean budget)."""
    if burst_factor <= 1.0 or duty >= 1.0:
        return rate
    phase = (t % period_s) / period_s
    if phase < duty:
        return rate * burst_factor
    return max(0.0, rate * (1.0 - burst_factor * duty) / (1.0 - duty))


def _arrival_times(rng: np.random.Generator, duration_s: float, rate: float,
                   burst_factor: float = 1.0, period_s: float = 2.0,
                   duty: float = 0.25) -> List[float]:
    """Non-homogeneous Poisson arrivals by thinning: candidates at the
    peak rate, each kept with probability ``r(t) / peak``. Pure function
    of the rng state."""
    if not (math.isfinite(duration_s) and math.isfinite(rate)
            and math.isfinite(burst_factor)):
        # the candidate loop walks to duration_s by exponential steps — a
        # non-finite bound or rate would walk forever
        raise ValueError(
            f"duration_s/rate/burst_factor must be finite "
            f"(got {duration_s!r}/{rate!r}/{burst_factor!r})")
    if burst_factor > 1.0 and burst_factor * duty > 1.0:
        # the off-phase rate clamps at 0 but cannot go negative — past
        # this point the burst excess is uncompensated and the generated
        # mean silently exceeds the declared rate (by burst_factor*duty)
        raise ValueError(
            f"burst_factor*duty must be <= 1 to preserve the declared "
            f"mean rate (got {burst_factor}*{duty} = "
            f"{burst_factor * duty:g})")
    peak = rate * max(burst_factor, 1.0)
    if peak <= 0.0 or duration_s <= 0.0:
        return []
    times: List[float] = []
    t = 0.0
    while True:
        t += float(rng.exponential(1.0 / peak))
        if t >= duration_s:
            break
        keep = float(rng.random())  # drawn unconditionally: count of draws
        # per candidate is fixed, so the stream is reproducible even if
        # the modulation params change
        if keep * peak <= _modulated_rate(t, rate, burst_factor,
                                          period_s, duty):
            times.append(round(t, 6))
    return times


def _heavy_tail_length(rng: np.random.Generator, tail: str, mean: float,
                       sigma: float, alpha: float, clip: int) -> int:
    """One heavy-tailed token count: ``lognormal`` (median ``mean``,
    shape ``sigma``) or ``pareto`` (shape ``alpha``, mean ``mean``)."""
    if tail == "pareto":
        # scale so the theoretical mean is ``mean`` (alpha > 1)
        xm = mean * (alpha - 1.0) / alpha if alpha > 1.0 else mean
        value = (1.0 + float(rng.pareto(alpha))) * xm
    else:
        value = float(rng.lognormal(math.log(max(mean, 1.0)), sigma))
    return int(min(max(round(value), 1), clip))


def _zipf_pmf(alpha: float, universe: int) -> "np.ndarray":
    """The bounded zipf distribution over key ranks 1..universe: key 0 is
    the hottest. ``alpha`` is the usual zipf exponent (1.0–1.3 matches
    measured serving fleets; higher = hotter head)."""
    if universe < 1:
        raise ValueError("hot_key_universe must be >= 1 when enabled")
    if alpha < 0.0:
        raise ValueError("hot_key_alpha must be >= 0")
    ranks = np.arange(1, universe + 1, dtype=np.float64)
    p = ranks ** -alpha
    return p / p.sum()


def _key_rng(seed: int, content_key: int) -> np.random.Generator:
    """The per-key generator behind "same key => byte-identical payload":
    a pure function of (trace seed, key), independent of record order."""
    return np.random.default_rng((int(seed), int(content_key)))


def _layout(model: str,
            shapes: Optional[Dict[str, List[int]]] = None,
            dtypes: Optional[Dict[str, str]] = None,
            ) -> Tuple[Dict[str, List[int]], Dict[str, str]]:
    if shapes is not None:
        return shapes, dict(dtypes or {})
    if model in _DEFAULT_LAYOUTS:
        default_shapes, default_dtypes = _DEFAULT_LAYOUTS[model]
        return dict(default_shapes), dict(default_dtypes)
    raise ValueError(
        f"no default tensor layout for model {model!r}: pass shapes/dtypes")


def poisson_burst(seed: int = 0, duration_s: float = 10.0, rate: float = 50.0,
                  burst_factor: float = 4.0, period_s: float = 2.0,
                  duty: float = 0.25, model: str = "simple",
                  shapes: Optional[Dict[str, List[int]]] = None,
                  dtypes: Optional[Dict[str, str]] = None,
                  ) -> List[TraceRecord]:
    """Unary traffic whose arrival rate flips between an on-phase burst
    (``rate * burst_factor`` for ``duty`` of each ``period_s``) and a
    quiet phase, keeping the long-run mean at ``rate`` req/s."""
    rng = np.random.default_rng(seed)
    shapes, dtypes = _layout(model, shapes, dtypes)
    return [TraceRecord(at_s=t, kind="unary", model=model,
                        shapes=shapes, dtypes=dtypes)
            for t in _arrival_times(rng, duration_s, rate, burst_factor,
                                    period_s, duty)]


def heavy_tail(seed: int = 0, duration_s: float = 10.0, rate: float = 10.0,
               tail: str = "lognormal", prompt_mean: float = 24.0,
               prompt_sigma: float = 1.0, output_mean: float = 8.0,
               output_sigma: float = 0.8, alpha: float = 1.8,
               max_prompt: int = 96, max_output: int = 32,
               model: str = "tiny_lm_generate",
               hot_key_alpha: float = 1.1,
               hot_key_universe: int = 0) -> List[TraceRecord]:
    """Streamed generations with heavy-tailed prompt/output token counts
    (``lognormal`` or ``pareto``) arriving as plain Poisson at ``rate``.

    ``hot_key_universe > 0`` arms the hot-key knob: each record draws a
    ``content_key`` from a bounded zipf(``hot_key_alpha``) over
    ``hot_key_universe`` keys, its token counts then come from a per-key
    generator — same key => identical record sizing AND byte-identical
    replay payloads (the session/prefix affinity + cache proof workload).
    The default 0 draws nothing extra, so pre-v3 specs stay
    byte-identical."""
    if tail not in ("lognormal", "pareto"):
        raise ValueError(f"unknown tail {tail!r} (lognormal|pareto)")
    rng = np.random.default_rng(seed)
    pmf = _zipf_pmf(hot_key_alpha, hot_key_universe) \
        if hot_key_universe else None
    records = []
    for t in _arrival_times(rng, duration_s, rate):
        if pmf is not None:
            key = int(rng.choice(hot_key_universe, p=pmf))
            krng = _key_rng(seed, key)
            records.append(TraceRecord(
                at_s=t, kind="generate_stream", model=model,
                content_key=key,
                prompt_tokens=_heavy_tail_length(
                    krng, tail, prompt_mean, prompt_sigma, alpha,
                    max_prompt),
                output_tokens=_heavy_tail_length(
                    krng, tail, output_mean, output_sigma, alpha,
                    max_output)))
            continue
        records.append(TraceRecord(
            at_s=t, kind="generate_stream", model=model,
            prompt_tokens=_heavy_tail_length(
                rng, tail, prompt_mean, prompt_sigma, alpha, max_prompt),
            output_tokens=_heavy_tail_length(
                rng, tail, output_mean, output_sigma, alpha, max_output)))
    return records


def mixed(seed: int = 0, duration_s: float = 10.0, rate: float = 50.0,
          stream_fraction: float = 0.2, seq_fraction: float = 0.1,
          burst_factor: float = 3.0, period_s: float = 2.0,
          duty: float = 0.25, tail: str = "lognormal",
          prompt_mean: float = 24.0, prompt_sigma: float = 1.0,
          output_mean: float = 8.0, output_sigma: float = 0.8,
          alpha: float = 1.8, max_prompt: int = 96, max_output: int = 32,
          seq_len_min: int = 2, seq_len_max: int = 6,
          seq_gap_s: float = 0.05, unary_model: str = "simple",
          stream_model: str = "tiny_lm_generate",
          seq_model: str = "simple_sequence",
          shard_fraction: float = 0.0, shards: int = 2,
          shard_model: str = "decoder_lm_tp_prefill",
          shard_batch: Optional[int] = None,
          disagg_fraction: float = 0.0,
          disagg_model: str = "decoder_lm_kv_decode",
          pipeline_fraction: float = 0.0,
          pipeline_model: str = "chain",
          hot_key_alpha: float = 1.1,
          hot_key_universe: int = 0,
          shapes: Optional[Dict[str, List[int]]] = None,
          dtypes: Optional[Dict[str, str]] = None) -> List[TraceRecord]:
    """Mixed-kind bursty traffic: each Poisson-burst arrival becomes a
    stream (``stream_fraction``), a whole sequence of ``seq_len_min..max``
    steps spaced ~``seq_gap_s`` apart (``seq_fraction``), a sharded
    scatter-gather logical request (``shard_fraction``; replayed through
    ``--shard-layout``), or a unary infer (the rest). ``rate`` counts
    *arrivals* — a sequence arrival fans out into several requests, so the
    offered request rate is slightly higher. The default
    ``shard_fraction=0`` draws nothing extra from the rng, so pre-sharding
    specs keep producing byte-identical traces.

    ``hot_key_universe > 0`` arms the hot-key knob on unary AND stream
    records: a zipf(``hot_key_alpha``)-drawn ``content_key`` per record
    (format v3), threaded by the replayer into per-key deterministic
    payload synthesis (same key => byte-identical inputs) and into
    ``routing="affinity"`` session keys — the proof workload for the
    client-side cache/singleflight layer. The default 0 draws nothing
    extra, so pre-v3 specs stay byte-identical. Sequences keep their own
    group affinity and carry no key.

    ``disagg_fraction > 0`` carves a slice of arrivals into
    ``prefill_decode`` records (format v5, stamped per record so v4
    loaders skip-and-count them): disaggregated prefill/decode sessions
    the replayer drives through ``client_tpu.disagg.DisaggClient``
    (``--roles``), sized by the same heavy-tail prompt/output draws as
    streams. The default 0 draws nothing extra, so pre-v5 specs keep
    producing byte-identical traces.

    ``pipeline_fraction > 0`` carves a slice of arrivals into
    ``pipeline`` records (format v6, stamped per record so v5 loaders
    skip-and-count them): client-orchestrated model-DAG runs the
    replayer drives through ``client_tpu.pipeline`` (``--pipeline``).
    The record's ``model`` names the pipeline, its shapes/dtypes the
    declared feeds. The default 0 draws nothing extra, so pre-v6 specs
    keep producing byte-identical traces."""
    if (stream_fraction + seq_fraction + shard_fraction
            + disagg_fraction + pipeline_fraction > 1.0):
        raise ValueError(
            "stream_fraction + seq_fraction + shard_fraction + "
            "disagg_fraction + pipeline_fraction must be <= 1")
    if seq_len_min < 1 or seq_len_max < seq_len_min:
        raise ValueError("need 1 <= seq_len_min <= seq_len_max")
    rng = np.random.default_rng(seed)
    unary_shapes, unary_dtypes = _layout(unary_model, shapes, dtypes)
    seq_shapes, seq_dtypes = _layout(seq_model)
    shard_shapes, shard_dtypes = (
        _layout(shard_model) if shard_fraction > 0.0 else ({}, {}))
    if shard_batch is not None:
        if shard_batch < shards:
            raise ValueError(f"shard_batch {shard_batch} < shards {shards}")
        shard_shapes = {k: [int(shard_batch)] + list(v[1:])
                        for k, v in shard_shapes.items()}
    pmf = _zipf_pmf(hot_key_alpha, hot_key_universe) \
        if hot_key_universe else None
    records: List[TraceRecord] = []
    group = 0
    for t in _arrival_times(rng, duration_s, rate, burst_factor,
                            period_s, duty):
        pick = float(rng.random())
        if shard_fraction and pick >= stream_fraction + seq_fraction \
                and pick < stream_fraction + seq_fraction + shard_fraction:
            records.append(TraceRecord(
                at_s=t, kind="sharded", model=shard_model,
                shapes=shard_shapes, dtypes=shard_dtypes, shards=shards))
            continue
        disagg_lo = stream_fraction + seq_fraction + shard_fraction
        if disagg_fraction and disagg_lo <= pick \
                < disagg_lo + disagg_fraction:
            # sized exactly like a stream (same heavy-tail draws), but
            # replayed as a two-leg disaggregated session
            records.append(TraceRecord(
                at_s=t, kind="prefill_decode", model=disagg_model,
                prompt_tokens=_heavy_tail_length(
                    rng, tail, prompt_mean, prompt_sigma, alpha, max_prompt),
                output_tokens=_heavy_tail_length(
                    rng, tail, output_mean, output_sigma, alpha, max_output),
                prefill_role="prefill", decode_role="decode"))
            continue
        pipe_lo = (stream_fraction + seq_fraction + shard_fraction
                   + disagg_fraction)
        if pipeline_fraction and pipe_lo <= pick \
                < pipe_lo + pipeline_fraction:
            # one DAG run per arrival; no extra rng draws, so
            # pipeline-less specs stay byte-identical
            pipe_shapes, pipe_dtypes = _layout(pipeline_model)
            records.append(TraceRecord(
                at_s=t, kind="pipeline", model=pipeline_model,
                shapes=pipe_shapes, dtypes=pipe_dtypes))
            continue
        if pick < stream_fraction:
            if pmf is not None:
                # keyed stream: sizing comes from the per-key generator so
                # equal keys are equal sessions (prompt AND output lengths)
                key = int(rng.choice(hot_key_universe, p=pmf))
                krng = _key_rng(seed, key)
                records.append(TraceRecord(
                    at_s=t, kind="generate_stream", model=stream_model,
                    content_key=key,
                    prompt_tokens=_heavy_tail_length(
                        krng, tail, prompt_mean, prompt_sigma, alpha,
                        max_prompt),
                    output_tokens=_heavy_tail_length(
                        krng, tail, output_mean, output_sigma, alpha,
                        max_output)))
                continue
            records.append(TraceRecord(
                at_s=t, kind="generate_stream", model=stream_model,
                prompt_tokens=_heavy_tail_length(
                    rng, tail, prompt_mean, prompt_sigma, alpha, max_prompt),
                output_tokens=_heavy_tail_length(
                    rng, tail, output_mean, output_sigma, alpha, max_output)))
        elif pick < stream_fraction + seq_fraction:
            group += 1
            steps = int(rng.integers(seq_len_min, seq_len_max + 1))
            at = t
            for i in range(steps):
                records.append(TraceRecord(
                    at_s=round(at, 6), kind="sequence", model=seq_model,
                    shapes=seq_shapes, dtypes=seq_dtypes,
                    seq_group=group, seq_index=i, seq_len=steps))
                at += float(rng.exponential(seq_gap_s))
        else:
            key = (int(rng.choice(hot_key_universe, p=pmf))
                   if pmf is not None else None)
            records.append(TraceRecord(
                at_s=t, kind="unary", model=unary_model,
                shapes=unary_shapes, dtypes=unary_dtypes,
                content_key=key))
    # stable by arrival: equal offsets keep insertion order, so a group's
    # steps never reorder even when gaps round to the same microsecond
    records.sort(key=lambda r: r.at_s)
    return records


def sharded(seed: int = 0, duration_s: float = 10.0, rate: float = 20.0,
            burst_factor: float = 1.0, period_s: float = 2.0,
            duty: float = 0.25, shards: int = 2,
            model: str = "decoder_lm_tp_prefill",
            batch: Optional[int] = None,
            shapes: Optional[Dict[str, List[int]]] = None,
            dtypes: Optional[Dict[str, str]] = None) -> List[TraceRecord]:
    """Sharded logical requests arriving Poisson (optionally bursty):
    each record is ONE logical scatter-gather infer whose tensors the
    replayer splits per its ``--shard-layout`` across ``shards``
    replica-pinned endpoints (``client_tpu.shard``). ``batch`` overrides
    the leading (shard) dimension of the model's default layout — spec
    strings can't carry shape dicts, and the sharded axis must be at
    least ``shards`` long. Records are stamped ``v=2`` so a v1 loader
    skips them (counted) instead of failing."""
    if shards < 1:
        raise ValueError("shards must be >= 1")
    rng = np.random.default_rng(seed)
    shapes, dtypes = _layout(model, shapes, dtypes)
    if batch is not None:
        if batch < shards:
            raise ValueError(f"batch {batch} < shards {shards}")
        shapes = {k: [int(batch)] + list(v[1:]) for k, v in shapes.items()}
    return [TraceRecord(at_s=t, kind="sharded", model=model,
                        shapes=shapes, dtypes=dtypes, shards=shards)
            for t in _arrival_times(rng, duration_s, rate, burst_factor,
                                    period_s, duty)]


def multi_tenant(seed: int = 0, duration_s: float = 10.0,
                 tenants: int = 2, rate: float = 20.0,
                 adversaries: int = 0, adversary_factor: float = 10.0,
                 burst_factor: float = 1.0, period_s: float = 2.0,
                 duty: float = 0.25, model: str = "simple",
                 hot_key_alpha: float = 1.1,
                 hot_key_universe: int = 0,
                 shapes: Optional[Dict[str, List[int]]] = None,
                 dtypes: Optional[Dict[str, str]] = None
                 ) -> List[TraceRecord]:
    """Multi-tenant unary traffic (format v4): ``tenants`` compliant
    tenants (``t0..tN-1``) each arriving Poisson at ``rate`` req/s, plus
    ``adversaries`` adversarial tenants (``adv0..``) each offering
    ``rate * adversary_factor`` — the noisy neighbor whose excess a
    quota must shed. Each tenant's arrival stream (and key draws) comes
    from its OWN child generator ``default_rng((seed, index))``, so
    adding an adversary never perturbs the compliant tenants' arrivals —
    the isolated and adversarial bench arms replay literally identical
    compliant traffic.

    ``hot_key_universe > 0`` draws a zipf ``content_key`` per record
    from a universe DELIBERATELY SHARED across tenants: two tenants
    constantly request the same hot content, so any cache hit, collapse
    or coalesce that crosses a tenant boundary would be exercised — the
    tenant-in-key isolation (``batch.plan_request``) is what this
    workload proves."""
    if tenants < 1:
        raise ValueError("tenants must be >= 1")
    if adversaries < 0:
        raise ValueError("adversaries must be >= 0")
    if adversary_factor <= 0.0:
        raise ValueError("adversary_factor must be > 0")
    shapes, dtypes = _layout(model, shapes, dtypes)
    pmf = _zipf_pmf(hot_key_alpha, hot_key_universe) \
        if hot_key_universe else None
    names = [f"t{i}" for i in range(tenants)]
    names += [f"adv{i}" for i in range(adversaries)]
    records: List[TraceRecord] = []
    for index, name in enumerate(names):
        trng = np.random.default_rng((int(seed), int(index)))
        tenant_rate = rate * (adversary_factor
                              if name.startswith("adv") else 1.0)
        for t in _arrival_times(trng, duration_s, tenant_rate,
                                burst_factor, period_s, duty):
            key = (int(trng.choice(hot_key_universe, p=pmf))
                   if pmf is not None else None)
            records.append(TraceRecord(
                at_s=t, kind="unary", model=model,
                shapes=shapes, dtypes=dtypes,
                content_key=key, tenant=name))
    # stable by arrival: equal offsets keep per-tenant insertion order
    records.sort(key=lambda r: r.at_s)
    return records


GENERATORS = {
    "poisson_burst": poisson_burst,
    "heavy_tail": heavy_tail,
    "mixed": mixed,
    "sharded": sharded,
    "multi_tenant": multi_tenant,
}

# spec params that must stay strings when parsed from a spec
_STR_PARAMS = {"model", "unary_model", "stream_model", "seq_model",
               "shard_model", "disagg_model", "pipeline_model", "tail"}


def parse_gen_spec(spec: str) -> Tuple[str, Dict[str, Any]]:
    """``name:key=value,...`` -> (generator name, kwargs). Values parse as
    int, then float, else stay strings."""
    name, _, rest = spec.partition(":")
    name = name.strip()
    if name not in GENERATORS:
        raise ValueError(
            f"unknown trace generator {name!r} "
            f"(one of {', '.join(sorted(GENERATORS))})")
    params: Dict[str, Any] = {}
    for part in filter(None, (p.strip() for p in rest.split(","))):
        key, sep, value = part.partition("=")
        if not sep:
            raise ValueError(f"malformed spec param {part!r} (want key=value)")
        key = key.strip()
        value = value.strip()
        if key in _STR_PARAMS:
            params[key] = value
            continue
        try:
            params[key] = int(value)
        except ValueError:
            try:
                params[key] = float(value)
            except ValueError:
                params[key] = value
    return name, params


def generate(spec: str, seed: int = 0,
             duration_s: Optional[float] = None) -> Trace:
    """Generate a trace from a ``name:k=v,...`` spec string. The header
    records the full provenance (spec, seed, resolved duration), so a
    written trace is self-describing and :func:`dumps_trace` of the result
    is byte-identical for identical ``(spec, seed)``. ``duration_s``
    OVERRIDES any duration in the spec — the capacity gate uses it to
    replay a shortened twin of a committed trace's workload shape."""
    name, params = parse_gen_spec(spec)
    if duration_s is not None:
        params["duration_s"] = duration_s
    try:
        records = GENERATORS[name](seed=seed, **params)
    except TypeError as e:
        raise ValueError(f"bad params for generator {name!r}: {e}") from None
    header = {
        "generator": name,
        "spec": spec,
        "seed": int(seed),
        "duration_s": params.get(
            "duration_s",
            # the generators' shared default
            10.0),
    }
    return Trace(header=header, records=records)
