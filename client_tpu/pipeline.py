"""Client-side model-DAG pipelines with arena-resident intermediates.

Every other layer in this package serves ONE model per logical request;
real products run chains and ensembles (tokenize -> embed -> rerank,
N-model voting) that Triton solves server-side with its "ensemble"
scheduler. This module rebuilds that orchestration CLIENT-side — where
it can span replicas, roles and cells — as a declared :class:`Pipeline`
graph of :class:`Stage`\\ s executed by :class:`PipelineClient` /
:class:`AioPipelineClient` over any frontend or pool::

    from client_tpu.pipeline import PipelineClient, chain_pipeline

    client = PipelineClient(["10.0.0.1:8000"], chain_pipeline())
    result = client.run({"RAW": raw})       # one DAG run
    result.as_numpy("SCORES")

Semantics (docs/pipelines.md has the full interaction matrix):

- **Validation is construction-time and typed.** Cycles, missing
  producers, dtype/shape incompatibilities, unconsumed stage outputs and
  unconsumed pipeline inputs all raise :class:`PipelineConfigError`
  before anything is sent.
- **Intermediates never round-trip the host.** Each consumed stage
  output lands in a :class:`~client_tpu.arena.ShmArena` lease bound to
  the request's ``InferRequestedOutput``; the consuming stage's
  ``InferInput`` references the SAME slab by shm handle. Region
  registrations ride the arena's per-``(endpoint, region)`` cache, so a
  steady-state run issues 0 region creates and 0 registration RPCs.
- **Slab residency is planned from tensor lifetimes.** ``Pipeline.plan``
  computes birth/death levels per intermediate from the DAG (the
  operator-lifetime shared-buffer planning of arXiv:2001.03288 applied
  across models); a tensor's lease is released the moment its last
  consumer settles, so a run's peak arena residency equals the plan's
  high-water mark.
- **One admission token, one attempt budget per logical run** (the
  shard.py contract): stages bypass the pool-level gate via
  ``routed_infer`` / ``pinned_infer`` and every stage dispatch draws its
  timeout from ONE shared :class:`~client_tpu.resilience.AttemptBudget`.
- **Failure is whole-run and typed.** A failed stage cancels unstarted
  dependents and raises :class:`StageFailed` naming the stage — never a
  partial result; every staged lease is released, eagerly for cancelled
  stages and at settle for in-flight ones.
- **Observability**: one span per run (frontend ``pipeline+<inner>``)
  with per-stage ``stage:<name>`` phases, plus a ``pipeline`` flight
  layer (plan / stage_dispatch / handoff / stage_settle / release
  events) whose attribution keys are ``pipeline:<stage>`` — the flight
  recorder names the slow stage.
"""

from __future__ import annotations

import asyncio
import re
import threading
import time
from collections import deque
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple, Union

import numpy as np

from . import flight as _flight
from ._tensor import InferInput, InferRequestedOutput, _release_quietly
from .pool import AioPoolClient, PoolClient, _PoolClientBase
from .utils import InferenceServerException, triton_to_np_dtype

__all__ = [
    "AioPipelineClient",
    "Pipeline",
    "PipelineClient",
    "PipelineConfigError",
    "PipelineError",
    "PipelineResult",
    "SlabPlan",
    "Stage",
    "StageFailed",
    "chain_pipeline",
    "resolve_pipeline",
]

_NAME_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_.\-]*$")
PIPELINE_INPUT = "$"  # the reserved "producer" name for pipeline feeds


class PipelineError(InferenceServerException):
    """Base for every typed pipeline error."""

    def __init__(self, msg: str, status: str = "PIPELINE"):
        super().__init__(msg, status=status)


class PipelineConfigError(PipelineError):
    """The pipeline declaration (or its composition with a substrate) is
    invalid: duplicate/illegal names, unresolvable references, cycles,
    dtype/shape incompatibilities, unconsumed outputs, sync/aio
    mismatch, endpoints the pool does not serve."""

    def __init__(self, msg: str):
        super().__init__(msg, status="PIPELINE_CONFIG")


class StageFailed(PipelineError):
    """One stage of a pipeline run failed. The LOGICAL run fails whole:
    unstarted dependents are cancelled, staged leases released, and the
    original error is preserved as ``cause`` — never a partial result."""

    def __init__(self, stage: str, url: Optional[str],
                 cause: BaseException):
        where = f" (endpoint {url})" if url else ""
        super().__init__(
            f"pipeline stage {stage!r}{where} failed: "
            f"{type(cause).__name__}: {cause}",
            status="PIPELINE_STAGE")
        self.stage = stage
        self.url = url
        self.cause = cause


def _check_name(kind: str, name: Any) -> str:
    if not isinstance(name, str) or not name:
        raise PipelineConfigError(f"{kind} name must be a non-empty "
                                  f"string, got {name!r}")
    if "." in name or "$" in name or not _NAME_RE.match(
            name.replace(".", "_")):
        raise PipelineConfigError(
            f"{kind} name {name!r} is illegal ('.' and '$' are reserved "
            "for tensor references)")
    return name


def _check_spec(owner: str, tensor: str, spec: Any) -> Tuple[str, List[int]]:
    """Validate one ``(dtype, shape)`` tensor declaration."""
    try:
        dtype, shape = spec
    except (TypeError, ValueError):
        raise PipelineConfigError(
            f"{owner}: tensor {tensor!r} spec must be (dtype, shape), "
            f"got {spec!r}")
    if triton_to_np_dtype(dtype) is None:
        raise PipelineConfigError(
            f"{owner}: tensor {tensor!r} has unknown dtype {dtype!r}")
    try:
        dims = [int(d) for d in shape]
    except (TypeError, ValueError):
        raise PipelineConfigError(
            f"{owner}: tensor {tensor!r} shape {shape!r} is not a list "
            "of ints")
    if not dims or any(d == 0 or d < -1 for d in dims):
        raise PipelineConfigError(
            f"{owner}: tensor {tensor!r} shape {dims} must be non-empty "
            "with every dim > 0 (or -1 for dynamic)")
    return str(dtype), dims


def _parse_ref(owner: str, ref: Any) -> Tuple[str, str]:
    """``"$.NAME"`` -> ``("$", NAME)``; ``"stage.TENSOR"`` ->
    ``(stage, TENSOR)``."""
    if not isinstance(ref, str) or ref.count(".") != 1:
        raise PipelineConfigError(
            f"{owner}: reference {ref!r} must be '$.INPUT' or "
            "'stage.TENSOR'")
    producer, tensor = ref.split(".", 1)
    if not producer or not tensor:
        raise PipelineConfigError(f"{owner}: reference {ref!r} is empty "
                                  "on one side of the '.'")
    return producer, tensor


def _shapes_compatible(a: Sequence[int], b: Sequence[int]) -> bool:
    return len(a) == len(b) and all(
        x == -1 or y == -1 or x == y for x, y in zip(a, b))


class Stage:
    """One node of a :class:`Pipeline`: a model invocation whose inputs
    are wired by tensor name from pipeline feeds (``"$.NAME"``) or
    upstream stage outputs (``"stage.TENSOR"``).

    ``outputs`` declares this stage's produced tensors as
    ``{name: (dtype, shape)}`` — the declaration the slab plan sizes
    leases from (dynamic ``-1`` dims or BYTES fall back to host-staged
    handoff). ``input_specs`` optionally declares expected ``(dtype,
    shape)`` per local input name for construction-time compatibility
    checks against the wired producer. ``endpoint`` pins the stage to
    one replica (pool substrate only), ``affinity_key`` routes it under
    ``routing="affinity"``, ``priority``/``tenant`` feed the run-level
    admission defaults (ONE token per run)."""

    __slots__ = ("name", "model", "inputs", "outputs", "input_specs",
                 "model_version", "priority", "tenant", "affinity_key",
                 "endpoint", "_refs")

    def __init__(self, name: str, model: str,
                 inputs: Dict[str, str],
                 outputs: Dict[str, Tuple[str, Sequence[int]]],
                 input_specs: Optional[Dict[str, Tuple[str,
                                                       Sequence[int]]]] = None,
                 model_version: str = "",
                 priority: int = 0,
                 tenant: Optional[str] = None,
                 affinity_key: Optional[str] = None,
                 endpoint: Optional[str] = None):
        self.name = _check_name("stage", name)
        if not isinstance(model, str) or not model:
            raise PipelineConfigError(
                f"stage {name!r}: model must be a non-empty string")
        self.model = model
        if not isinstance(inputs, dict) or not inputs:
            raise PipelineConfigError(
                f"stage {name!r}: inputs must be a non-empty "
                "{local: reference} dict")
        if not isinstance(outputs, dict) or not outputs:
            raise PipelineConfigError(
                f"stage {name!r}: outputs must be a non-empty "
                "{tensor: (dtype, shape)} dict")
        self.inputs = dict(inputs)
        self._refs = {
            local: _parse_ref(f"stage {name!r} input {local!r}", ref)
            for local, ref in self.inputs.items()}
        self.outputs = {
            _check_name(f"stage {name!r} output", t):
                _check_spec(f"stage {name!r}", t, spec)
            for t, spec in outputs.items()}
        self.input_specs = {
            local: _check_spec(f"stage {name!r} input_specs", local, spec)
            for local, spec in (input_specs or {}).items()}
        unknown = set(self.input_specs) - set(self.inputs)
        if unknown:
            raise PipelineConfigError(
                f"stage {name!r}: input_specs for unwired inputs "
                f"{sorted(unknown)}")
        self.model_version = model_version
        self.priority = int(priority)
        self.tenant = tenant
        self.affinity_key = affinity_key
        self.endpoint = endpoint


class SlabPlan:
    """Lifetime-based arena residency plan for one pipeline.

    Each plannable intermediate (consumed downstream, static shape,
    non-BYTES) is assigned a ``[birth, death]`` level span — produced at
    its stage's topological level, dead after its last consumer's level
    — and ``high_water_bytes`` is the max over levels of the summed
    size-class bytes of tensors live at that level. Because the clients
    allocate a tensor's lease at producer dispatch and release it the
    moment its last consumer settles, a run's observed peak residency
    equals this high-water mark (asserted in tests/test_pipeline.py)."""

    __slots__ = ("tensors", "level_bytes", "high_water_bytes",
                 "host_staged")

    def __init__(self, tensors: Dict[str, Dict[str, Any]],
                 level_bytes: List[int],
                 host_staged: Dict[str, str]):
        self.tensors = tensors
        self.level_bytes = level_bytes
        self.high_water_bytes = max(level_bytes) if level_bytes else 0
        self.host_staged = host_staged

    def describe(self) -> Dict[str, Any]:
        return {
            "high_water_bytes": self.high_water_bytes,
            "level_bytes": list(self.level_bytes),
            "tensors": {k: dict(v) for k, v in self.tensors.items()},
            "host_staged": dict(self.host_staged),
        }


class Pipeline:
    """A validated model DAG: named :class:`Stage`\\ s, declared pipeline
    ``inputs`` (``{name: (dtype, shape)}``) and exported ``outputs``
    (``{name: "stage.TENSOR"}``).

    Construction validates the whole graph — duplicate names, dangling
    references, cycles, dtype/shape incompatibilities (against declared
    ``input_specs``), unconsumed stage outputs, unconsumed pipeline
    inputs — raising :class:`PipelineConfigError` with the offending
    edge named."""

    def __init__(self, stages: Sequence[Stage],
                 inputs: Dict[str, Tuple[str, Sequence[int]]],
                 outputs: Dict[str, str],
                 name: str = "pipeline"):
        self.name = _check_name("pipeline", name)
        if not stages:
            raise PipelineConfigError("a pipeline needs at least one "
                                      "stage")
        self.stages: Dict[str, Stage] = {}
        for st in stages:
            if not isinstance(st, Stage):
                raise PipelineConfigError(
                    f"stages must be Stage instances, got "
                    f"{type(st).__name__}")
            if st.name in self.stages or st.name == PIPELINE_INPUT:
                raise PipelineConfigError(
                    f"duplicate stage name {st.name!r}")
            self.stages[st.name] = st
        if not isinstance(inputs, dict) or not inputs:
            raise PipelineConfigError(
                "pipeline inputs must be a non-empty "
                "{name: (dtype, shape)} dict")
        self.inputs = {
            _check_name("pipeline input", n):
                _check_spec("pipeline", n, spec)
            for n, spec in inputs.items()}
        if not isinstance(outputs, dict) or not outputs:
            raise PipelineConfigError(
                "pipeline outputs must be a non-empty {name: "
                "'stage.TENSOR'} dict")
        self._validate_wiring()
        self._toposort()
        self._validate_compat()
        self.exports: Dict[str, Tuple[str, str]] = {}
        for out_name, ref in outputs.items():
            _check_name("pipeline output", out_name)
            producer, tensor = _parse_ref(
                f"pipeline output {out_name!r}", ref)
            if producer == PIPELINE_INPUT:
                raise PipelineConfigError(
                    f"pipeline output {out_name!r} cannot re-export a "
                    f"pipeline input ({ref!r})")
            if producer not in self.stages:
                raise PipelineConfigError(
                    f"pipeline output {out_name!r} references unknown "
                    f"stage {producer!r}")
            if tensor not in self.stages[producer].outputs:
                raise PipelineConfigError(
                    f"pipeline output {out_name!r} references "
                    f"{producer}.{tensor} but stage {producer!r} does "
                    f"not declare output {tensor!r}")
            self.exports[out_name] = (producer, tensor)
        self._validate_coverage()

    # -- validation ---------------------------------------------------------
    def _validate_wiring(self) -> None:
        """Every reference resolves: consumers, tensor key maps."""
        self.consumers: Dict[str, List[str]] = {}
        self.stage_deps: Dict[str, Set[str]] = {}
        self.dependents: Dict[str, List[str]] = {s: [] for s in self.stages}
        self.stage_upstream: Dict[str, List[str]] = {}
        for sname, st in self.stages.items():
            deps: Set[str] = set()
            upstream: Set[str] = set()
            for local, (producer, tensor) in st._refs.items():
                where = f"stage {sname!r} input {local!r}"
                if producer == PIPELINE_INPUT:
                    if tensor not in self.inputs:
                        raise PipelineConfigError(
                            f"{where} references undeclared pipeline "
                            f"input {tensor!r}")
                    continue
                if producer == sname:
                    raise PipelineConfigError(
                        f"{where} references its own stage "
                        f"({producer}.{tensor}): a stage cannot consume "
                        "itself")
                if producer not in self.stages:
                    raise PipelineConfigError(
                        f"{where} references unknown stage "
                        f"{producer!r}")
                if tensor not in self.stages[producer].outputs:
                    raise PipelineConfigError(
                        f"{where} references {producer}.{tensor} but "
                        f"stage {producer!r} does not declare output "
                        f"{tensor!r}")
                key = f"{producer}.{tensor}"
                cons = self.consumers.setdefault(key, [])
                if sname not in cons:
                    cons.append(sname)
                deps.add(producer)
                upstream.add(key)
            self.stage_deps[sname] = deps
            self.stage_upstream[sname] = sorted(upstream)
        for sname, deps in self.stage_deps.items():
            for d in deps:
                self.dependents[d].append(sname)

    def _toposort(self) -> None:
        """Kahn's algorithm in declaration order; leftovers name the
        cycle. Levels are longest-path depths (the plan's time axis)."""
        left = {s: len(d) for s, d in self.stage_deps.items()}
        order: List[str] = []
        ready = [s for s in self.stages if left[s] == 0]
        self.level: Dict[str, int] = {s: 0 for s in ready}
        while ready:
            s = ready.pop(0)
            order.append(s)
            for d in self.dependents[s]:
                left[d] -= 1
                self.level[d] = max(self.level.get(d, 0),
                                    self.level[s] + 1)
                if left[d] == 0:
                    ready.append(d)
        if len(order) != len(self.stages):
            cyclic = sorted(s for s in self.stages if s not in order)
            raise PipelineConfigError(
                f"pipeline has a cycle through stages {cyclic}")
        self.order = order
        self.depth = 1 + max(self.level.values()) if self.level else 0

    def _validate_compat(self) -> None:
        """Declared ``input_specs`` vs the wired producer's declaration
        (dtype equality, shape rank + per-dim with -1 wildcards)."""
        for sname, st in self.stages.items():
            for local, (producer, tensor) in st._refs.items():
                spec = st.input_specs.get(local)
                if spec is None:
                    continue
                if producer == PIPELINE_INPUT:
                    src_dt, src_shape = self.inputs[tensor]
                    src = f"pipeline input {tensor!r}"
                else:
                    src_dt, src_shape = \
                        self.stages[producer].outputs[tensor]
                    src = f"{producer}.{tensor}"
                want_dt, want_shape = spec
                if src_dt != want_dt:
                    raise PipelineConfigError(
                        f"stage {sname!r} input {local!r} expects dtype "
                        f"{want_dt} but {src} produces {src_dt}")
                if not _shapes_compatible(src_shape, want_shape):
                    raise PipelineConfigError(
                        f"stage {sname!r} input {local!r} expects shape "
                        f"{want_shape} but {src} produces {src_shape}")

    def _validate_coverage(self) -> None:
        """No dead tensors: every stage output is consumed or exported,
        every pipeline input is consumed."""
        exported = {f"{s}.{t}" for s, t in self.exports.values()}
        dead = sorted(
            f"{sname}.{t}" for sname, st in self.stages.items()
            for t in st.outputs
            if f"{sname}.{t}" not in self.consumers
            and f"{sname}.{t}" not in exported)
        if dead:
            raise PipelineConfigError(
                f"unconsumed stage outputs {dead}: every declared output "
                "must be consumed downstream or exported as a pipeline "
                "output")
        consumed_feeds = {
            tensor for st in self.stages.values()
            for producer, tensor in st._refs.values()
            if producer == PIPELINE_INPUT}
        unused = sorted(set(self.inputs) - consumed_feeds)
        if unused:
            raise PipelineConfigError(
                f"unconsumed pipeline inputs {unused}: every declared "
                "input must be wired into at least one stage")

    # -- planning -----------------------------------------------------------
    def plan(self, class_for=None) -> SlabPlan:
        """Compute the lifetime-based slab plan. ``class_for`` maps a
        tensor's nbytes to its arena size class (pass the serving
        arena's ``_class_for`` so planned bytes equal leased bytes;
        default identity plans raw bytes)."""
        class_for = class_for or (lambda n: n)
        tensors: Dict[str, Dict[str, Any]] = {}
        host_staged: Dict[str, str] = {}
        n_levels = self.depth
        level_bytes = [0] * n_levels
        for sname, st in self.stages.items():
            for tname, (dtype, shape) in st.outputs.items():
                key = f"{sname}.{tname}"
                cons = self.consumers.get(key, [])
                if not cons:
                    host_staged[key] = "exported-only (plain wire)"
                    continue
                np_dt = triton_to_np_dtype(dtype)
                if dtype == "BYTES" or np_dt is None \
                        or np_dt == np.object_:
                    host_staged[key] = "BYTES dtype (host staged)"
                    continue
                if any(d < 0 for d in shape):
                    host_staged[key] = "dynamic shape (host staged)"
                    continue
                nbytes = int(np.prod(shape)) * np.dtype(np_dt).itemsize
                cls = int(class_for(max(1, nbytes)))
                birth = self.level[sname]
                death = max(self.level[c] for c in cons)
                tensors[key] = {
                    "nbytes": nbytes, "class_bytes": cls,
                    "birth": birth, "death": death,
                    "consumers": list(cons),
                }
                for lvl in range(birth, death + 1):
                    level_bytes[lvl] += cls
        return SlabPlan(tensors, level_bytes, host_staged)

    # -- introspection ------------------------------------------------------
    def describe(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "order": list(self.order),
            "depth": self.depth,
            "stages": {
                s: {"model": st.model, "level": self.level[s],
                    "inputs": dict(st.inputs),
                    "outputs": {t: [dt, list(sh)]
                                for t, (dt, sh) in st.outputs.items()},
                    "endpoint": st.endpoint,
                    "affinity_key": st.affinity_key}
                for s, st in self.stages.items()},
            "inputs": {n: [dt, list(sh)]
                       for n, (dt, sh) in self.inputs.items()},
            "outputs": {n: f"{s}.{t}"
                        for n, (s, t) in self.exports.items()},
        }

    # -- parsing ------------------------------------------------------------
    _IN_RE = re.compile(r"^in\s+(\w+)\s*:\s*(\w+)\s*\[([0-9,\s\-]+)\]$")
    _OUT_RE = re.compile(r"^out\s+(\w+)\s*=\s*([\w$]+\.\w+)$")
    _STAGE_RE = re.compile(
        r"^(\w+)\s*=\s*([\w\-./]+?)(?:@([\w\-.]+))?\s*"
        r"\(([^)]*)\)\s*->\s*(.+)$")
    _ODECL_RE = re.compile(r"^(\w+)\s*:\s*(\w+)\s*\[([0-9,\s\-]+)\]$")

    @classmethod
    def parse(cls, spec: str, name: str = "pipeline") -> "Pipeline":
        """Parse a compact semicolon-separated pipeline spec::

            in RAW:INT32[1,16];
            tok=chain_tokenize(RAW=$.RAW)->TOKENS:INT32[1,16];
            emb=chain_embed(TOKENS=tok.TOKENS)->EMBED:FP32[1,16,32];
            out SCORES=emb.EMBED

        Segments: ``in NAME:DTYPE[dims]`` declares a pipeline input,
        ``stage=model[@version](LOCAL=ref,...)->OUT:DTYPE[dims]+...``
        declares a stage (multiple outputs joined with ``+``), and
        ``out NAME=stage.TENSOR`` exports a pipeline output."""
        inputs: Dict[str, Tuple[str, List[int]]] = {}
        outputs: Dict[str, str] = {}
        stages: List[Stage] = []
        for raw_seg in spec.split(";"):
            seg = raw_seg.strip()
            if not seg:
                continue
            m = cls._IN_RE.match(seg)
            if m:
                inputs[m.group(1)] = (
                    m.group(2),
                    [int(d) for d in m.group(3).split(",")])
                continue
            m = cls._OUT_RE.match(seg)
            if m:
                outputs[m.group(1)] = m.group(2)
                continue
            m = cls._STAGE_RE.match(seg)
            if m:
                sname, model, version, wires, odecls = m.groups()
                wiring: Dict[str, str] = {}
                for w in wires.split(","):
                    w = w.strip()
                    if not w:
                        continue
                    if "=" not in w:
                        raise PipelineConfigError(
                            f"pipeline spec: bad wire {w!r} in segment "
                            f"{seg!r} (want LOCAL=ref)")
                    local, ref = w.split("=", 1)
                    wiring[local.strip()] = ref.strip()
                outs: Dict[str, Tuple[str, List[int]]] = {}
                for od in odecls.split("+"):
                    om = cls._ODECL_RE.match(od.strip())
                    if not om:
                        raise PipelineConfigError(
                            f"pipeline spec: bad output declaration "
                            f"{od.strip()!r} (want NAME:DTYPE[dims])")
                    outs[om.group(1)] = (
                        om.group(2),
                        [int(d) for d in om.group(3).split(",")])
                stages.append(Stage(sname, model, wiring, outs,
                                    model_version=version or ""))
                continue
            raise PipelineConfigError(
                f"pipeline spec: cannot parse segment {seg!r}")
        return cls(stages, inputs, outputs, name=name)


EMBED_DIM = 32  # mirrors client_tpu.models.chain.EMBED_DIM (asserted there)


def chain_pipeline(batch: int = 1, length: int = 16) -> Pipeline:
    """The standard 3-stage chain over the ``models/`` zoo's chain
    fixtures (``chain_tokenize`` -> ``chain_embed`` -> ``chain_rerank``)
    — the graph whose runs are asserted bit-exact against the fused
    ``chain_fused`` single-model reference."""
    return Pipeline(
        name="chain",
        stages=[
            Stage("tokenize", "chain_tokenize",
                  inputs={"RAW": "$.RAW"},
                  outputs={"TOKENS": ("INT32", [batch, length])}),
            Stage("embed", "chain_embed",
                  inputs={"TOKENS": "tokenize.TOKENS"},
                  input_specs={"TOKENS": ("INT32", [batch, length])},
                  outputs={"EMBED": ("FP32",
                                     [batch, length, EMBED_DIM])}),
            Stage("rerank", "chain_rerank",
                  inputs={"EMBED": "embed.EMBED"},
                  input_specs={"EMBED": ("FP32",
                                         [batch, length, EMBED_DIM])},
                  outputs={"SCORES": ("FP32", [batch, length])}),
        ],
        inputs={"RAW": ("INT32", [batch, length])},
        outputs={"SCORES": "rerank.SCORES"},
    )


def resolve_pipeline(spec: Union[str, Pipeline]) -> Pipeline:
    """A CLI-friendly resolver: a :class:`Pipeline` passes through, the
    builtin name ``"chain"`` builds :func:`chain_pipeline`, anything
    with an ``=`` parses as a :meth:`Pipeline.parse` spec."""
    if isinstance(spec, Pipeline):
        return spec
    if spec == "chain":
        return chain_pipeline()
    if "=" in spec:
        return Pipeline.parse(spec)
    raise PipelineConfigError(
        f"unknown pipeline {spec!r}: pass 'chain' or an inline "
        "'in ...; stage=model(...)->...; out ...' spec")


class PipelineResult:
    """One completed DAG run: exported tensors (host arrays, safe after
    the run's leases are gone), per-stage wall latencies, and the run's
    observed-vs-planned arena residency."""

    __slots__ = ("outputs", "stage_latency_s", "duration_s",
                 "arena_high_water_bytes", "plan_high_water_bytes")

    def __init__(self, outputs: Dict[str, np.ndarray],
                 stage_latency_s: Dict[str, float], duration_s: float,
                 arena_high_water_bytes: int,
                 plan_high_water_bytes: int):
        self.outputs = outputs
        self.stage_latency_s = stage_latency_s
        self.duration_s = duration_s
        self.arena_high_water_bytes = arena_high_water_bytes
        self.plan_high_water_bytes = plan_high_water_bytes

    def as_numpy(self, name: str) -> np.ndarray:
        try:
            return self.outputs[name]
        except KeyError:
            raise PipelineError(
                f"unknown pipeline output {name!r} (have "
                f"{sorted(self.outputs)})")

    def describe(self) -> Dict[str, Any]:
        return {
            "outputs": {n: [str(a.dtype), list(a.shape)]
                        for n, a in self.outputs.items()},
            "stage_ms": {s: round(v * 1e3, 3)
                         for s, v in self.stage_latency_s.items()},
            "duration_ms": round(self.duration_s * 1e3, 3),
            "arena_high_water_bytes": self.arena_high_water_bytes,
            "plan_high_water_bytes": self.plan_high_water_bytes,
        }


class _TensorState:
    """One intermediate's run-time residency: the arena lease (or the
    host-staged value), the ACTUAL produced shape, and the set of
    consumer stages still outstanding — the lease is released the
    moment this set empties."""

    __slots__ = ("lease", "value", "dtype", "shape", "nbytes",
                 "class_bytes", "pending")

    def __init__(self, dtype: str, pending: Set[str], lease=None,
                 nbytes: int = 0, class_bytes: int = 0):
        self.lease = lease
        self.value: Optional[np.ndarray] = None
        self.dtype = dtype
        self.shape: Optional[List[int]] = None
        self.nbytes = nbytes
        self.class_bytes = class_bytes
        self.pending = pending


class _RunState:
    """Book-keeping for ONE logical run (tensors, settle/abandon sets,
    residency high-water). ``lock`` serializes the failure path's
    late-settle callbacks (worker threads) against the coordinator."""

    __slots__ = ("feeds", "tensors", "exports", "stage_lat", "settled",
                 "abandoned", "failed", "lock", "resident",
                 "high_water", "t0")

    def __init__(self, feeds: Dict[str, np.ndarray]):
        self.feeds = feeds
        self.tensors: Dict[str, _TensorState] = {}
        self.exports: Dict[str, np.ndarray] = {}
        self.stage_lat: Dict[str, float] = {}
        self.settled: Set[str] = set()
        self.abandoned: Set[str] = set()
        self.failed = False
        self.lock = threading.Lock()
        self.resident = 0
        self.high_water = 0
        self.t0 = time.monotonic()

    def lease_acquired(self, class_bytes: int) -> None:
        self.resident += class_bytes
        if self.resident > self.high_water:
            self.high_water = self.resident

    def lease_released(self, class_bytes: int) -> None:
        self.resident -= class_bytes


class _PipelineBase:
    """DAG-execution logic shared by the sync and asyncio clients."""

    _AIO = False

    def __init__(self, client: Any, pipeline: Pipeline,
                 arena: Any = None):
        if not isinstance(pipeline, Pipeline):
            raise PipelineConfigError(
                f"need a Pipeline, got {type(pipeline).__name__}")
        kind = type(client).__name__
        if "Batching" in kind:
            raise PipelineConfigError(
                "pipelines cannot ride the coalescing dispatcher: a "
                "batch window would stack stage requests across runs — "
                "wrap the PoolClient itself")
        if "Sharded" in kind:
            raise PipelineConfigError(
                "pipelines cannot wrap a ShardedClient: stage dispatch "
                "is whole-request — give the pipeline the pool and "
                "shard within a stage's own serving path instead")
        if not hasattr(client, "infer"):
            raise PipelineConfigError(
                f"pipeline substrate {kind} has no infer()")
        inner_aio = getattr(client, "_AIO", None)
        if inner_aio is None:
            inner_aio = asyncio.iscoroutinefunction(
                getattr(type(client), "infer", None))
        if bool(inner_aio) != self._AIO:
            raise PipelineConfigError(
                "sync PipelineClient needs a sync substrate and "
                "AioPipelineClient an asyncio one (sync/aio mismatch)")
        self.inner = client
        self.pipeline = pipeline
        self._pool = isinstance(client, _PoolClientBase)
        if self._pool:
            pool_urls = {ep.url for ep in client.pool.endpoints}
            bad = sorted(st.endpoint for st in pipeline.stages.values()
                         if st.endpoint and st.endpoint not in pool_urls)
            if bad:
                raise PipelineConfigError(
                    f"pipeline stages pin endpoints the pool does not "
                    f"serve: {bad}")
        else:
            pinned = sorted(
                st.name for st in pipeline.stages.values()
                if st.endpoint or st.affinity_key)
            if pinned:
                raise PipelineConfigError(
                    f"stages {pinned} declare endpoint/affinity routing "
                    "but the substrate is not a pool")
        if arena is True:
            from .arena import default_arena
            arena = default_arena()
        if arena is None:
            getter = getattr(client, "arena", None)
            arena = getter() if callable(getter) else None
        self._arena = arena
        class_for = (arena._class_for if arena is not None
                     else (lambda n: n))
        self._plan = pipeline.plan(class_for)
        # run-level admission defaults derived from the stage
        # declarations (explicit run kwargs win)
        self._default_priority = max(
            (st.priority for st in pipeline.stages.values()), default=0)
        self._default_tenant = next(
            (st.tenant for st in pipeline.stages.values()
             if st.tenant is not None), None)
        self._stats_lock = threading.Lock()
        self._runs = 0
        self._failures = 0
        self._observed_high_water = 0
        self._stage_ms: Dict[str, deque] = {
            s: deque(maxlen=256) for s in pipeline.order}

    # -- composition rejections (typed) ------------------------------------
    def coalescing(self, **kwargs):
        raise PipelineConfigError(
            "pipeline runs cannot be coalesced: a batch window would "
            "stack stage requests across DAG runs")

    def generate_stream(self, *args, **kwargs):
        raise PipelineConfigError(
            "generate_stream is not a pipeline stage: decode streams "
            "are sessions, not DAG nodes (see client_tpu.disagg)")

    def start_stream(self, *args, **kwargs):
        raise PipelineConfigError(
            "bidi streams cannot host a pipeline: stage dispatch is "
            "per-request")

    # -- delegation ---------------------------------------------------------
    @property
    def _FRONTEND(self) -> str:
        return "pipeline+" + getattr(self.inner, "_FRONTEND", "client")

    def telemetry(self):
        getter = getattr(self.inner, "telemetry", None)
        return getter() if callable(getter) else None

    def arena(self):
        return self._arena

    def admission(self):
        getter = getattr(self.inner, "admission", None)
        return getter() if callable(getter) else None

    def endpoint_stats(self):
        return self.inner.endpoint_stats()

    def plan(self) -> SlabPlan:
        return self._plan

    def describe(self) -> Dict[str, Any]:
        d = self.pipeline.describe()
        d["plan"] = self._plan.describe()
        return d

    def __getattr__(self, name: str):
        if name.startswith("_") or name == "inner":
            raise AttributeError(name)
        return getattr(self.inner, name)

    def stats(self) -> Dict[str, Any]:
        with self._stats_lock:
            stages = {}
            for s, dq in self._stage_ms.items():
                if not dq:
                    stages[s] = {"count": 0}
                    continue
                vals = sorted(dq)
                n = len(vals)
                stages[s] = {
                    "count": n,
                    "avg_ms": round(sum(vals) / n, 3),
                    "p50_ms": round(vals[n // 2], 3),
                    "max_ms": round(vals[-1], 3),
                }
            return {
                "pipeline": self.pipeline.name,
                "runs": self._runs,
                "failures": self._failures,
                "plan_high_water_bytes": self._plan.high_water_bytes,
                "observed_high_water_bytes": self._observed_high_water,
                "stages": stages,
            }

    # -- request validation -------------------------------------------------
    def _check_kwargs(self, kwargs: Dict[str, Any]) -> None:
        if kwargs.get("sequence_id"):
            raise PipelineConfigError(
                "sequence requests cannot drive a pipeline run: "
                "sequence state is replica-local, stages are not")
        if "outputs" in kwargs:
            raise PipelineConfigError(
                "run() owns per-stage output placement; export tensors "
                "via the pipeline's outputs declaration instead of "
                "outputs=")

    def _check_feeds(self, feeds: Any) -> Dict[str, np.ndarray]:
        if not isinstance(feeds, dict):
            raise PipelineConfigError(
                f"run() feeds must be a {{name: ndarray}} dict, got "
                f"{type(feeds).__name__}")
        declared = self.pipeline.inputs
        missing = sorted(set(declared) - set(feeds))
        extra = sorted(set(feeds) - set(declared))
        if missing or extra:
            raise PipelineConfigError(
                f"feeds do not match declared pipeline inputs "
                f"(missing {missing}, unexpected {extra})")
        checked: Dict[str, np.ndarray] = {}
        for name, arr in feeds.items():
            dtype, shape = declared[name]
            arr = np.ascontiguousarray(arr)
            want = np.dtype(triton_to_np_dtype(dtype))
            if dtype != "BYTES" and arr.dtype != want:
                raise PipelineConfigError(
                    f"feed {name!r} dtype {arr.dtype} does not match "
                    f"declared {dtype} ({want})")
            if not _shapes_compatible(list(arr.shape), shape):
                raise PipelineConfigError(
                    f"feed {name!r} shape {list(arr.shape)} does not "
                    f"match declared {shape}")
            checked[name] = arr
        return checked

    # -- stage request assembly --------------------------------------------
    def _build_stage_request(self, run: _RunState, stage: Stage):
        """Assemble one stage's wire tensors on the coordinator: inputs
        reference upstream slabs by shm handle (the zero-copy handoff),
        consumed outputs land in fresh arena leases sized by the plan."""
        pl = self.pipeline
        inputs: List[InferInput] = []
        for local, (producer, tensor) in stage._refs.items():
            if producer == PIPELINE_INPUT:
                arr = run.feeds[tensor]
                inp = InferInput(local, list(arr.shape),
                                 pl.inputs[tensor][0])
                inp.set_data_from_numpy(arr)
            else:
                key = f"{producer}.{tensor}"
                ts = run.tensors[key]
                inp = InferInput(local, list(ts.shape), ts.dtype)
                if ts.lease is not None:
                    ts.lease.bind_input(inp)
                    _flight.note("pipeline", "handoff", url=stage.name,
                                 tensor=key, bytes=ts.nbytes)
                else:
                    inp.set_data_from_numpy(ts.value)
            inputs.append(inp)
        outputs: List[InferRequestedOutput] = []
        for tname in stage.outputs:
            key = f"{stage.name}.{tname}"
            spec = self._plan.tensors.get(key)
            out = InferRequestedOutput(tname)
            pending = set(pl.consumers.get(key, ()))
            dtype = stage.outputs[tname][0]
            if spec is not None and self._arena is not None:
                lease = self._arena.lease(spec["nbytes"])
                lease.bind_output(out)
                ts = _TensorState(dtype, pending, lease=lease,
                                  nbytes=spec["nbytes"],
                                  class_bytes=lease.byte_size)
                with run.lock:
                    run.tensors[key] = ts
                    run.lease_acquired(lease.byte_size)
            else:
                with run.lock:
                    run.tensors[key] = _TensorState(dtype, pending)
            outputs.append(out)
        return inputs, outputs

    def _stage_kwargs(self, kwargs: Dict[str, Any], stage: Stage,
                      remaining: Optional[float]) -> Dict[str, Any]:
        kw = dict(kwargs)
        kw.pop("priority", None)
        kw.pop("tenant", None)
        if remaining is not None:
            kw["client_timeout"] = remaining
        request_id = kw.get("request_id")
        if request_id:
            kw["request_id"] = f"{request_id}.{stage.name}"
        if stage.model_version:
            kw["model_version"] = stage.model_version
        return kw

    def _stage_url(self, stage: Stage) -> Optional[str]:
        if stage.endpoint:
            return stage.endpoint
        if self._pool:
            eps = self.inner.pool.endpoints
            if len(eps) == 1:
                return eps[0].url
        return None

    # -- settle / release ---------------------------------------------------
    def _settle_stage(self, run: _RunState, stage: Stage,
                      res: Any) -> None:
        """Extract the stage's outputs (exports copied out of leased
        slabs) and decrement upstream pending-consumer sets — releasing
        each upstream lease the moment this stage was its LAST consumer.

        Lease ownership is single-ref: the result's ``release_arena``
        and this run's ``_drop_tensor`` share the ONE reference created
        at dispatch, and only the run releases it (at last-consumer
        settle) — downstream ``bind_input`` handoffs read the live slab
        until then."""
        pl = self.pipeline
        exported = {(s, t): out_name
                    for out_name, (s, t) in pl.exports.items()}
        for tname, (_dt, declared_shape) in stage.outputs.items():
            key = f"{stage.name}.{tname}"
            ts = run.tensors[key]
            arr = res.as_numpy(tname)
            if arr is None:
                raise PipelineError(
                    f"stage {stage.name!r} response is missing "
                    f"declared output {tname!r}")
            if not _shapes_compatible(list(arr.shape),
                                      declared_shape):
                raise PipelineError(
                    f"stage {stage.name!r} output {tname!r} came "
                    f"back {list(arr.shape)}, declared "
                    f"{declared_shape}")
            ts.shape = list(arr.shape)
            if ts.lease is None:
                ts.value = arr
            out_name = exported.get((stage.name, tname))
            if out_name is not None:
                # leased views die with the slab: exports are copied
                # to host arrays the caller owns outright
                run.exports[out_name] = (
                    np.array(arr) if ts.lease is not None else arr)
        with run.lock:
            run.settled.add(stage.name)
            for key in pl.stage_upstream[stage.name]:
                ts = run.tensors.get(key)
                if ts is None:
                    continue
                ts.pending.discard(stage.name)
                if not ts.pending:
                    self._drop_tensor(run, key, ts)

    def _drop_tensor(self, run: _RunState, key: str,
                     ts: _TensorState) -> None:
        """Release one tensor's run-owned lease (caller holds
        ``run.lock``); idempotent."""
        if ts.lease is not None:
            _release_quietly(ts.lease)
            run.lease_released(ts.class_bytes)
            _flight.note("pipeline", "release", tensor=key,
                         bytes=ts.class_bytes)
            ts.lease = None
        ts.value = None

    def _stage_abandon(self, run: _RunState, sname: str) -> None:
        """Failure-path cleanup for one unsettled stage: drop its own
        dispatched output leases and its claims on upstream tensors
        (releasing any it was the last outstanding consumer of)."""
        pl = self.pipeline
        stage = pl.stages[sname]
        with run.lock:
            if sname in run.settled or sname in run.abandoned:
                return
            run.abandoned.add(sname)
            for tname in stage.outputs:
                key = f"{sname}.{tname}"
                ts = run.tensors.get(key)
                if ts is not None:
                    self._drop_tensor(run, key, ts)
            for key in pl.stage_upstream[sname]:
                ts = run.tensors.get(key)
                if ts is None:
                    continue
                ts.pending.discard(sname)
                if not ts.pending:
                    self._drop_tensor(run, key, ts)

    def _abandon_all_unsettled(self, run: _RunState) -> None:
        for sname in self.pipeline.order:
            self._stage_abandon(run, sname)

    def _finish_run(self, run: _RunState) -> PipelineResult:
        with run.lock:
            # defensive: coverage validation guarantees every leased
            # tensor has consumers, so nothing should be live here
            for key, ts in run.tensors.items():
                if ts.lease is not None:
                    self._drop_tensor(run, key, ts)
        return PipelineResult(
            outputs=run.exports,
            stage_latency_s=dict(run.stage_lat),
            duration_s=time.monotonic() - run.t0,
            arena_high_water_bytes=run.high_water,
            plan_high_water_bytes=self._plan.high_water_bytes)

    def _account_run(self, run: _RunState,
                     error: Optional[BaseException]) -> None:
        with self._stats_lock:
            self._runs += 1
            if error is not None:
                self._failures += 1
            if run.high_water > self._observed_high_water:
                self._observed_high_water = run.high_water
            for s, v in run.stage_lat.items():
                self._stage_ms[s].append(v * 1e3)

    # -- observability -------------------------------------------------------
    def _span_begin(self):
        tel = self.telemetry()
        if tel is None:
            return None, None
        return tel, tel.begin(self._FRONTEND, self.pipeline.name,
                              op="pipeline_run")

    def _note_done(self, tel, span,
                   marks: List[Tuple[str, int, int]],
                   error: Optional[BaseException]) -> None:
        if tel is None:
            return
        # per-stage sub-spans fold HERE on the caller's side, from the
        # workers' completion marks (the flight scratch is context-local
        # — worker-thread notes would be dropped)
        if span is not None:
            for sname, start_ns, end_ns in list(marks):
                span.phase(f"stage:{sname}", start_ns, end_ns)
        tel.finish(span, error)

    def _budget_policy(self):
        return getattr(self.inner, "_budget_policy", None)


class PipelineClient(_PipelineBase):
    """Synchronous DAG executor over a :class:`~client_tpu.pool.PoolClient`
    (or any sync frontend). Independent stages fan out on an internal
    thread pool; the coordinator (the calling thread) owns every flight
    event, lease release and dependent dispatch, so a run's causal
    timeline and residency accounting are single-threaded truths."""

    _AIO = False

    def __init__(self, client: Union[Any, Sequence[str]],
                 pipeline: Pipeline, protocol: str = "http",
                 arena: Any = None,
                 executor_workers: Optional[int] = None,
                 **pool_kwargs):
        """``executor_workers``: stage fan-out thread pool size — a run
        holds up to ``width(DAG)`` workers for its round trip (default
        ``max(8, 2 * n_stages)``)."""
        owns = False
        if not hasattr(client, "infer") and not isinstance(client, str):
            try:
                urls = [str(u) for u in client]
            except TypeError:
                raise PipelineConfigError(
                    f"unusable pipeline substrate "
                    f"{type(client).__name__!r}: pass a client with "
                    "an infer() method, a url, or a sequence of urls"
                ) from None
            pool_kwargs.setdefault("shm_arena", True)
            client = PoolClient(urls, protocol=protocol, **pool_kwargs)
            owns = True
        elif pool_kwargs:
            raise PipelineConfigError(
                "pool kwargs are only accepted when PipelineClient "
                "builds the pool itself (pass urls, not a client)")
        try:
            super().__init__(client, pipeline, arena=arena)
        except BaseException:
            if owns:
                client.close()
            raise
        self._owns = owns
        self._executor_workers = (
            executor_workers if executor_workers
            else max(8, 2 * len(pipeline.stages)))
        self._executor_lock = threading.Lock()
        self._executor: Optional[ThreadPoolExecutor] = None

    def _get_executor(self) -> ThreadPoolExecutor:
        with self._executor_lock:
            if self._executor is None:
                self._executor = ThreadPoolExecutor(
                    max_workers=self._executor_workers,
                    thread_name_prefix="client_tpu_pipeline")
            return self._executor

    def close(self) -> None:
        with self._executor_lock:
            if self._executor is not None:
                self._executor.shutdown(wait=False)
                self._executor = None
        if self._owns:
            self.inner.close()

    def __enter__(self) -> "PipelineClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- execution -----------------------------------------------------------
    def run(self, feeds: Dict[str, np.ndarray],
            **kwargs) -> PipelineResult:
        """Execute one DAG run over ``feeds`` (``{input: ndarray}``).
        Accepts the usual request kwargs (``client_timeout``,
        ``priority``, ``tenant``, ``request_id``, ``headers``);
        per-stage request ids are stamped ``<rid>.<stage>``."""
        kwargs = dict(kwargs)
        self._check_kwargs(kwargs)
        feeds = self._check_feeds(feeds)
        scratch = _flight.layer_begin(
            self.telemetry(), "pipeline", self.pipeline.name)
        if scratch is None:
            return self._run_admitted(feeds, kwargs)
        try:
            result = self._run_admitted(feeds, kwargs)
        except BaseException as e:
            _flight.layer_commit(self.telemetry(), scratch, error=e)
            raise
        _flight.layer_commit(self.telemetry(), scratch)
        return result

    def _run_admitted(self, feeds, kwargs) -> PipelineResult:
        """ONE admission token covers the whole DAG run (stages bypass
        the pool gate via routed_infer/pinned_infer) — the shard.py
        contract, so a half-admitted fan-out can never deadlock the
        controller against itself."""
        inner = self.inner
        ctrl = self.admission()
        if ctrl is None:
            return self._run_dag(feeds, kwargs)
        deadline = inner._admission_deadline(kwargs.get("client_timeout"))
        t0_ns = time.perf_counter_ns()
        token = ctrl.acquire(
            kwargs.get("priority") or self._default_priority, deadline,
            tenant=kwargs.get("tenant") or self._default_tenant)
        admission_phase = ((t0_ns, time.perf_counter_ns())
                           if token.waited_s else None)
        t0 = time.monotonic()
        try:
            result = self._run_dag(feeds, kwargs, admission_phase)
        except BaseException as e:
            inner._admission_settle(
                token, t0, getattr(e, "cause", None) or e)
            raise
        inner._admission_settle(token, t0, None)
        return result

    def _run_dag(self, feeds, kwargs,
                 admission_phase=None) -> PipelineResult:
        from .resilience import AttemptBudget

        pl = self.pipeline
        tel, span = self._span_begin()
        if span is not None and admission_phase is not None:
            span.phase("admission_queue", *admission_phase)
        budget = AttemptBudget(self._budget_policy(),
                               kwargs.get("client_timeout"))
        run = _RunState(feeds)
        marks: List[Tuple[str, int, int]] = []
        error: Optional[BaseException] = None
        try:
            _flight.note(
                "pipeline", "plan", stages=len(pl.order),
                tensors=len(self._plan.tensors),
                planned_bytes=self._plan.high_water_bytes)
            executor = self._get_executor()
            deps_left = {s: len(pl.stage_deps[s]) for s in pl.order}
            futures: Dict[Any, str] = {}
            failed: Optional[Tuple[str, BaseException]] = None

            def dispatch(sname: str) -> None:
                stage = pl.stages[sname]
                remaining = budget.attempt_timeout_s()  # shared budget
                inputs, outputs = self._build_stage_request(run, stage)
                _flight.note("pipeline", "stage_dispatch", url=sname,
                             model=stage.model)
                skw = self._stage_kwargs(kwargs, stage, remaining)
                fut = executor.submit(self._call_stage, stage, inputs,
                                      outputs, skw)
                futures[fut] = sname

            for sname in pl.order:
                if deps_left[sname]:
                    continue
                try:
                    dispatch(sname)
                except BaseException as e:
                    failed = (sname, e)
                    break
            while futures and failed is None:
                done, _ = wait(set(futures),
                               return_when=FIRST_COMPLETED)
                for f in done:
                    sname = futures.pop(f)
                    exc = f.exception()
                    if exc is not None:
                        self._stage_abandon(run, sname)
                        if failed is None:
                            failed = (sname, exc)
                        continue
                    res, t_start, t_end = f.result()
                    try:
                        self._settle_stage(run, pl.stages[sname], res)
                    except BaseException as e:
                        self._stage_abandon(run, sname)
                        if failed is None:
                            failed = (sname, e)
                        continue
                    marks.append((sname, t_start, t_end))
                    run.stage_lat[sname] = (t_end - t_start) * 1e-9
                    _flight.note(
                        "pipeline", "stage_settle", url=sname,
                        ms=round((t_end - t_start) * 1e-6, 3))
                    if failed is not None:
                        continue
                    for dep in pl.dependents[sname]:
                        deps_left[dep] -= 1
                        if deps_left[dep] == 0:
                            try:
                                dispatch(dep)
                            except BaseException as e:
                                failed = (dep, e)
                                break
            if failed is not None:
                self._fail_cleanup(run, futures)
                sname, cause = failed
                if isinstance(cause, StageFailed):
                    raise cause
                raise StageFailed(
                    sname, self._stage_url(pl.stages[sname]), cause)
            return self._finish_run(run)
        except BaseException as e:
            error = e
            raise
        finally:
            self._note_done(tel, span, marks, error)
            self._account_run(run, error)

    def _call_stage(self, stage: Stage, inputs, outputs, kw):
        """Worker-thread leg: ONE stage request through the substrate
        (its own routing/resilience decision). Returns completion marks
        for the coordinator to fold — flight/lease bookkeeping never
        happens here."""
        t_start = time.perf_counter_ns()
        res = self._dispatch_infer(stage, inputs, outputs, kw)
        return res, t_start, time.perf_counter_ns()

    def _dispatch_infer(self, stage: Stage, inputs, outputs, kw):
        inner = self.inner
        if self._pool:
            if stage.endpoint:
                return inner.pinned_infer(stage.endpoint, stage.model,
                                          inputs, outputs=outputs, **kw)
            if stage.affinity_key:
                kw = dict(kw, affinity_key=stage.affinity_key)
            return inner.routed_infer(stage.model, inputs,
                                      outputs=outputs, **kw)
        return inner.infer(stage.model, inputs, outputs=outputs, **kw)

    def _fail_cleanup(self, run: _RunState,
                      futures: Dict[Any, str]) -> None:
        """Fail fast and WHOLE: cancel what never started (their leases
        release here, on the coordinator), let in-flight stages settle
        in the background — a late-settle callback drops the result's
        adopted refs and the stage's staged leases, so a failed run
        leaks nothing."""
        with run.lock:
            run.failed = True
        for f, sname in list(futures.items()):
            if f.cancel():
                _flight.note("pipeline", "stage_cancelled", url=sname)
                self._stage_abandon(run, sname)
            else:
                f.add_done_callback(
                    lambda fut, s=sname: self._late_settle(run, s, fut))
        # stages still waiting on dependencies never dispatched: release
        # their upstream claims so settled producers' slabs free now
        self._abandon_all_unsettled_except(run, set(futures.values()))

    def _abandon_all_unsettled_except(self, run: _RunState,
                                      in_flight: Set[str]) -> None:
        for sname in self.pipeline.order:
            if sname in in_flight:
                continue
            self._stage_abandon(run, sname)

    def _late_settle(self, run: _RunState, sname: str, fut) -> None:
        # the stage's output leases live in run.tensors (single-ref
        # protocol) — abandoning releases them whether the straggler
        # succeeded or died
        self._stage_abandon(run, sname)


class AioPipelineClient(_PipelineBase):
    """Asyncio twin of :class:`PipelineClient` over an
    :class:`~client_tpu.pool.AioPoolClient` (or any asyncio frontend):
    stage fan-out as tasks, so the first failure TRULY cancels sibling
    stages mid-flight before raising :class:`StageFailed`."""

    _AIO = True

    def __init__(self, client: Union[Any, Sequence[str]],
                 pipeline: Pipeline, protocol: str = "http",
                 arena: Any = None, **pool_kwargs):
        owns = False
        if not hasattr(client, "infer") and not isinstance(client, str):
            try:
                urls = [str(u) for u in client]
            except TypeError:
                raise PipelineConfigError(
                    f"unusable pipeline substrate "
                    f"{type(client).__name__!r}: pass a client with "
                    "an infer() method, a url, or a sequence of urls"
                ) from None
            pool_kwargs.setdefault("shm_arena", True)
            client = AioPoolClient(urls, protocol=protocol,
                                   **pool_kwargs)
            owns = True
        elif pool_kwargs:
            raise PipelineConfigError(
                "pool kwargs are only accepted when AioPipelineClient "
                "builds the pool itself (pass urls, not a client)")
        try:
            super().__init__(client, pipeline, arena=arena)
        except BaseException:
            if owns:
                # close() is a coroutine; abandon endpoints synchronously
                client._abandon(client.pool.endpoints)
            raise
        self._owns = owns

    async def close(self) -> None:
        if self._owns:
            await self.inner.close()

    async def __aenter__(self) -> "AioPipelineClient":
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    # -- execution -----------------------------------------------------------
    async def run(self, feeds: Dict[str, np.ndarray],
                  **kwargs) -> PipelineResult:
        kwargs = dict(kwargs)
        self._check_kwargs(kwargs)
        feeds = self._check_feeds(feeds)
        scratch = _flight.layer_begin(
            self.telemetry(), "pipeline", self.pipeline.name)
        if scratch is None:
            return await self._run_admitted(feeds, kwargs)
        try:
            result = await self._run_admitted(feeds, kwargs)
        except BaseException as e:
            _flight.layer_commit(self.telemetry(), scratch, error=e)
            raise
        _flight.layer_commit(self.telemetry(), scratch)
        return result

    async def _run_admitted(self, feeds, kwargs) -> PipelineResult:
        inner = self.inner
        ctrl = self.admission()
        if ctrl is None:
            return await self._run_dag(feeds, kwargs)
        deadline = inner._admission_deadline(kwargs.get("client_timeout"))
        t0_ns = time.perf_counter_ns()
        token = await ctrl.acquire_async(
            kwargs.get("priority") or self._default_priority, deadline,
            tenant=kwargs.get("tenant") or self._default_tenant)
        admission_phase = ((t0_ns, time.perf_counter_ns())
                           if token.waited_s else None)
        t0 = time.monotonic()
        try:
            result = await self._run_dag(feeds, kwargs, admission_phase)
        except BaseException as e:
            inner._admission_settle(
                token, t0, getattr(e, "cause", None) or e)
            raise
        inner._admission_settle(token, t0, None)
        return result

    async def _run_dag(self, feeds, kwargs,
                       admission_phase=None) -> PipelineResult:
        from .resilience import AttemptBudget

        pl = self.pipeline
        tel, span = self._span_begin()
        if span is not None and admission_phase is not None:
            span.phase("admission_queue", *admission_phase)
        budget = AttemptBudget(self._budget_policy(),
                               kwargs.get("client_timeout"))
        run = _RunState(feeds)
        marks: List[Tuple[str, int, int]] = []
        error: Optional[BaseException] = None
        try:
            _flight.note(
                "pipeline", "plan", stages=len(pl.order),
                tensors=len(self._plan.tensors),
                planned_bytes=self._plan.high_water_bytes)
            deps_left = {s: len(pl.stage_deps[s]) for s in pl.order}
            tasks: Dict[Any, str] = {}
            failed: Optional[Tuple[str, BaseException]] = None

            def dispatch(sname: str) -> None:
                stage = pl.stages[sname]
                remaining = budget.attempt_timeout_s()
                inputs, outputs = self._build_stage_request(run, stage)
                _flight.note("pipeline", "stage_dispatch", url=sname,
                             model=stage.model)
                skw = self._stage_kwargs(kwargs, stage, remaining)
                task = asyncio.ensure_future(
                    self._call_stage(stage, inputs, outputs, skw))
                tasks[task] = sname

            for sname in pl.order:
                if deps_left[sname]:
                    continue
                try:
                    dispatch(sname)
                except BaseException as e:
                    failed = (sname, e)
                    break
            try:
                while tasks and failed is None:
                    done, _ = await asyncio.wait(
                        set(tasks), return_when=asyncio.FIRST_COMPLETED)
                    for t in done:
                        sname = tasks.pop(t)
                        if t.cancelled():
                            self._stage_abandon(run, sname)
                            continue
                        exc = t.exception()
                        if exc is not None:
                            self._stage_abandon(run, sname)
                            if failed is None:
                                failed = (sname, exc)
                            continue
                        res, t_start, t_end = t.result()
                        try:
                            self._settle_stage(run, pl.stages[sname],
                                               res)
                        except BaseException as e:
                            self._stage_abandon(run, sname)
                            if failed is None:
                                failed = (sname, e)
                            continue
                        marks.append((sname, t_start, t_end))
                        run.stage_lat[sname] = (t_end - t_start) * 1e-9
                        _flight.note(
                            "pipeline", "stage_settle", url=sname,
                            ms=round((t_end - t_start) * 1e-6, 3))
                        if failed is not None:
                            continue
                        for dep in pl.dependents[sname]:
                            deps_left[dep] -= 1
                            if deps_left[dep] == 0:
                                try:
                                    dispatch(dep)
                                except BaseException as e:
                                    failed = (dep, e)
                                    break
                if failed is not None:
                    # true cancellation: sibling stages die mid-flight
                    await self._cancel_all(run, tasks)
                    self._abandon_all_unsettled(run)
                    sname, cause = failed
                    if isinstance(cause, StageFailed):
                        raise cause
                    raise StageFailed(
                        sname, self._stage_url(pl.stages[sname]), cause)
            except asyncio.CancelledError:
                await self._cancel_all(run, tasks)
                self._abandon_all_unsettled(run)
                raise
            return self._finish_run(run)
        except BaseException as e:
            error = e
            raise
        finally:
            self._note_done(tel, span, marks, error)
            self._account_run(run, error)

    async def _call_stage(self, stage: Stage, inputs, outputs, kw):
        t_start = time.perf_counter_ns()
        res = await self._dispatch_infer(stage, inputs, outputs, kw)
        return res, t_start, time.perf_counter_ns()

    async def _dispatch_infer(self, stage: Stage, inputs, outputs, kw):
        inner = self.inner
        if self._pool:
            if stage.endpoint:
                return await inner.pinned_infer(
                    stage.endpoint, stage.model, inputs,
                    outputs=outputs, **kw)
            if stage.affinity_key:
                kw = dict(kw, affinity_key=stage.affinity_key)
            return await inner.routed_infer(stage.model, inputs,
                                            outputs=outputs, **kw)
        return await inner.infer(stage.model, inputs, outputs=outputs,
                                 **kw)

    async def _cancel_all(self, run: _RunState,
                          tasks: Dict[Any, str]) -> None:
        for t, sname in list(tasks.items()):
            t.cancel()
        for t, sname in list(tasks.items()):
            try:
                await t
            except BaseException:
                pass
            self._stage_abandon(run, sname)
        tasks.clear()
