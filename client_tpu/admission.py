"""Adaptive admission control: concurrency limiting + priority-lane shedding.

Under overload, a client that keeps queueing doomed work destroys the p99
of the traffic it *could* have served: every request waits behind requests
that will miss their deadlines anyway, and nothing distinguishes "the
fleet is slow" from "the fleet is drowning". This module closes ROADMAP
item 2's admission half:

- :class:`AdaptiveLimiter` — an adaptive concurrency limit over observed
  completion latency. ``mode="aimd"`` grows the limit additively on
  in-SLO completions and decays it multiplicatively when latency diverges
  from the declared SLO target (or, with no target, from a minRTT EWMA);
  ``mode="gradient"`` is a gradient2-style tracker (long-RTT over
  short-RTT gradient with a sqrt queue allowance). Both are bounded by
  ``min_limit``/``max_limit`` and cheap enough for the per-request path
  (one short lock).

- :class:`AdmissionController` — the limiter plus **priority lanes with
  deadline-aware shedding**. Requests carry a KServe ``priority`` (0 =
  default; per the reference semantics LOWER values are MORE important)
  mapped to a lane; when the limiter is saturated:

  * requests that cannot possibly meet their deadline (remaining budget
    below the limiter's latency estimate) are rejected immediately —
    reject cheap and early beats timing out late;
  * low-priority lanes are rejected immediately instead of queueing;
  * everyone else waits in a bounded per-lane **LIFO** queue — the
    NEWEST waiter is admitted first, so fresh requests beat requests
    that have already burned most of their budget waiting — bounded by
    ``max_queue`` and ``max_queue_wait_s``.

- :class:`AdmissionRejected` — the typed fault every shed raises. It is a
  *client-local* rejection (nothing touched the wire):
  ``resilience.classify_fault`` maps it to the ``SHED`` domain (never
  retried, never counted against breakers or outlier ejection) and the
  perf/replay harnesses count it as ``shed``, not ``error``.

- **Tenancy** (``AdmissionController(tenancy=...)``, see
  ``client_tpu.tenancy``): each lane's waiter stack becomes per-tenant
  virtual queues drained weighted-fair — the tenant with the smallest
  virtual finish time drains next (its vtime advances by ``1/weight``
  per admit), LIFO within the tenant, so one tenant's backlog can no
  longer starve its lane-mates while a single tenant sees the exact
  legacy LIFO order. Token-bucket quotas shed over-quota requests at
  the door with the typed reason ``over_quota`` and an HONEST
  ``retry_after_s`` (the bucket's refill eta). ``over_quota`` is a
  POLICY denial, deliberately absent from ``SPILL_REASONS`` — a
  federation layer must never launder a quota away by spilling the
  excess to another cell.

Wiring lives in ``client_tpu.pool`` (``PoolClient(admission=...)``
acquires one token per pooled infer — one token covers the whole
failover/hedge engine run, and a coalesced batch from
``client_tpu.batch`` admits ONCE per wire dispatch by construction) and
``client_tpu.observe`` (``Telemetry.attach_admission`` exports
``client_tpu_admission_shed_total{lane,reason}``, per-lane queue depth,
and the live limit/inflight gauges). See docs/admission.md.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import flight as _flight
from .utils import InferenceServerException

__all__ = [
    "AdaptiveLimiter",
    "AdmissionController",
    "AdmissionRejected",
    "AdmissionToken",
    "LANE_DEFAULT",
    "LANE_HIGH",
    "LANE_LOW",
    "SHED_DEADLINE",
    "SHED_ENDPOINT_SATURATED",
    "SHED_OVER_QUOTA",
    "SHED_QUEUE_FULL",
    "SHED_QUEUE_TIMEOUT",
    "SHED_SATURATED",
    "SPILL_REASONS",
    "default_lane_map",
    "is_spill_signal",
]

# shed reasons (the {reason} label on client_tpu_admission_shed_total)
SHED_SATURATED = "saturated"            # low lane rejected at the door
SHED_DEADLINE = "deadline"              # could not possibly meet its deadline
SHED_QUEUE_FULL = "queue_full"          # lane queue at capacity
SHED_QUEUE_TIMEOUT = "queue_timeout"    # waited max_queue_wait_s, still saturated
SHED_ENDPOINT_SATURATED = "endpoint_saturated"  # every replica at its limit
SHED_OVER_QUOTA = "over_quota"          # tenant token-bucket quota exhausted

LANE_HIGH = "high"
LANE_DEFAULT = "default"
LANE_LOW = "low"

# the controller's exception status; resilience.classify_fault keys the
# SHED domain off this string so the two modules never import each other
ADMISSION_REJECTED_STATUS = "ADMISSION_REJECTED"

# shed reasons that double as CAPACITY signals: every one of them means
# "this cell/pool cannot take the request right now", so a multi-cell
# layer (client_tpu.federation) may answer it by SPILLING the request to
# another cell instead of surfacing the shed to the caller. A rejection
# reason that is NOT about capacity must be left out of this set so it
# never silently moves traffic — concretely, SHED_OVER_QUOTA is a POLICY
# denial: spilling a tenant's over-quota excess to a sibling cell would
# launder the quota away, so it stays out of this set by design.
SPILL_REASONS = frozenset({
    SHED_SATURATED,
    SHED_DEADLINE,
    SHED_QUEUE_FULL,
    SHED_QUEUE_TIMEOUT,
    SHED_ENDPOINT_SATURATED,
})


def is_spill_signal(exc: BaseException) -> bool:
    """Whether this fault is an admission shed a locality-spillover
    layer may answer by re-routing to another cell (see
    ``SPILL_REASONS``). The federation layer calls this on every
    ``AdmissionRejected`` its home cell raises — the shed→spill bridge
    that turns saturation into graceful degradation instead of a
    user-visible error."""
    return (isinstance(exc, AdmissionRejected)
            and exc.reason in SPILL_REASONS)


class AdmissionRejected(InferenceServerException):
    """A request shed by admission control before it touched the wire.

    ``reason`` is one of the ``SHED_*`` constants, ``lane`` the priority
    lane it was judged in, ``tenant`` the tenant it was judged AS (None
    for tenantless traffic). ``retry_after_s`` is an honest backpressure
    hint when known: the token bucket's refill eta for ``over_quota``
    sheds, the limiter's minRTT eta for capacity sheds. ``classify_fault``
    maps this to the ``SHED`` domain: never retried, never a
    breaker/ejection signal, and counted as ``shed`` (not ``error``) by
    the perf/replay harnesses."""

    def __init__(self, reason: str, lane: str = LANE_DEFAULT,
                 msg: Optional[str] = None,
                 retry_after_s: Optional[float] = None,
                 tenant: Optional[str] = None):
        super().__init__(
            msg or (f"admission rejected ({reason}; lane={lane}"
                    + (f"; tenant={tenant}" if tenant is not None else "")
                    + (f"; retry_after={retry_after_s:.3f}s"
                       if retry_after_s is not None else "")
                    + ")"),
            status=ADMISSION_REJECTED_STATUS)
        self.reason = reason
        self.lane = lane
        self.tenant = tenant
        self.retry_after_s = retry_after_s
        # set True once a telemetry counter has seen this instance, so a
        # shed that crosses layers (endpoint select -> pool wrapper) is
        # exported exactly once
        self.counted = False


def default_lane_map(priority: int) -> Tuple[str, int]:
    """KServe ``priority`` -> ``(lane, rank)``; rank 0 drains first.

    The reference semantics: priority 0 means "the model's default
    priority level"; EXPLICIT values are ordered with lower = more
    important (1 is the highest priority). So ``1`` rides the high lane,
    ``0``/unset the default lane, and everything ``>= 2`` the low lane —
    the lane shed first under saturation."""
    if priority == 1:
        return LANE_HIGH, 0
    if priority in (0, None):
        return LANE_DEFAULT, 1
    return LANE_LOW, 2


class AdaptiveLimiter:
    """An adaptive concurrency limit over observed completion latency.

    ``mode="aimd"`` (default): every in-SLO completion grows the limit by
    ``increase / limit`` (additive, amortized — one full unit of limit per
    ``limit`` good completions); a breach (an error, or latency above the
    SLO ``target_ms`` — or above ``tolerance * minRTT`` when no target is
    declared) decays it multiplicatively by ``decay``, at most once per
    ``cooldown_s`` so one burst of queued completions doesn't collapse
    the limit to the floor in a single RTT.

    ``mode="gradient"`` (gradient2-style): tracks a slow long-RTT EWMA
    and a fast short-RTT EWMA; the limit tracks
    ``limit * clamp(long/short) + sqrt(limit)`` (the sqrt term is the
    queue allowance), smoothed by ``smoothing``. Errors and SLO-target
    breaches decay multiplicatively exactly like aimd.

    The limiter also maintains a **minRTT EWMA** (fast to track down,
    slow to drift up) used as the service-time estimate for
    deadline-aware shedding (:meth:`eta_s`).

    Thread-safe; every operation is one short lock."""

    def __init__(
        self,
        mode: str = "aimd",
        target_ms: Optional[float] = None,
        initial_limit: float = 8.0,
        min_limit: int = 1,
        max_limit: int = 256,
        increase: float = 1.0,
        decay: float = 0.7,
        tolerance: float = 2.0,
        cooldown_s: float = 0.1,
        smoothing: float = 0.2,
        clock: Callable[[], float] = time.monotonic,
    ):
        if mode not in ("aimd", "gradient"):
            raise ValueError(f"unknown limiter mode {mode!r} (aimd|gradient)")
        if min_limit < 1 or max_limit < min_limit:
            raise ValueError("need 1 <= min_limit <= max_limit")
        if not 0.0 < decay < 1.0:
            raise ValueError("decay must be in (0, 1)")
        if tolerance < 1.0:
            raise ValueError("tolerance must be >= 1")
        self.mode = mode
        self.target_ms = target_ms
        self.min_limit = int(min_limit)
        self.max_limit = int(max_limit)
        self.increase = float(increase)
        self.decay = float(decay)
        self.tolerance = float(tolerance)
        self.cooldown_s = float(cooldown_s)
        self.smoothing = float(smoothing)
        self._clock = clock
        self._lock = threading.Lock()
        self._limit = float(min(max(initial_limit, min_limit), max_limit))
        self._minrtt_s: Optional[float] = None
        self._short_s: Optional[float] = None  # fast EWMA (gradient mode)
        self._long_s: Optional[float] = None   # slow EWMA (gradient mode)
        self._last_decay = 0.0
        self.good_total = 0
        self.breach_total = 0
        self.decay_total = 0

    # EWMA alphas: minRTT tracks down fast and drifts up slowly (so a
    # transient fast completion re-anchors it but sustained queueing can't
    # inflate it into vouching for doomed deadlines); gradient's long RTT
    # moves an order of magnitude slower than its short RTT
    _MINRTT_DOWN = 0.5
    _MINRTT_UP = 0.02
    _SHORT_ALPHA = 0.3
    _LONG_ALPHA = 0.03

    @property
    def limit(self) -> float:
        with self._lock:
            return self._limit

    def limit_int(self) -> int:
        """The whole-request admission bound (never below 1)."""
        with self._lock:
            return max(1, int(self._limit))

    def would_admit(self, inflight: int) -> bool:
        return inflight < self.limit_int()

    def eta_s(self) -> Optional[float]:
        """The current service-time estimate (minRTT EWMA) used for
        deadline feasibility; None until a completion has been seen."""
        with self._lock:
            return self._minrtt_s

    def minrtt_ms(self) -> Optional[float]:
        eta = self.eta_s()
        return eta * 1e3 if eta is not None else None

    # -- feeding --------------------------------------------------------------
    def on_result(self, latency_s: Optional[float], ok: bool = True) -> bool:
        """Feed one completion. ``latency_s=None`` with ``ok=True`` is a
        neutral release (no signal — e.g. a request shed downstream);
        ``ok=False`` is a breach whatever the latency (an overload-class
        error is the strongest "back off" signal there is). Returns
        whether the completion counted as in-SLO."""
        if latency_s is None and ok:
            return True
        with self._lock:
            now = self._clock()
            if latency_s is not None:
                self._feed_rtts(latency_s)
            breach = not ok or self._is_breach(latency_s)
            if breach:
                self.breach_total += 1
                if now - self._last_decay >= self.cooldown_s:
                    self._limit = max(
                        float(self.min_limit), self._limit * self.decay)
                    self._last_decay = now
                    self.decay_total += 1
                return False
            self.good_total += 1
            if self.mode == "gradient":
                self._gradient_step()
            else:
                self._limit = min(
                    float(self.max_limit),
                    self._limit + self.increase / max(self._limit, 1.0))
            return True

    def _feed_rtts(self, latency_s: float) -> None:
        if latency_s < 0.0:
            return
        m = self._minrtt_s
        if m is None:
            self._minrtt_s = latency_s
        else:
            alpha = self._MINRTT_DOWN if latency_s < m else self._MINRTT_UP
            self._minrtt_s = m + alpha * (latency_s - m)
        s = self._short_s
        self._short_s = (latency_s if s is None
                         else s + self._SHORT_ALPHA * (latency_s - s))
        lo = self._long_s
        self._long_s = (latency_s if lo is None
                        else lo + self._LONG_ALPHA * (latency_s - lo))

    def _is_breach(self, latency_s: Optional[float]) -> bool:
        if latency_s is None:
            return False
        if self.target_ms is not None:
            return latency_s * 1e3 > self.target_ms
        m = self._minrtt_s
        return m is not None and latency_s > self.tolerance * m

    def _gradient_step(self) -> None:
        short, long = self._short_s, self._long_s
        if not short or not long:
            return
        # gradient < 1 means latency is rising above its long-run norm:
        # shrink; clamped so one outlier sample can neither halve nor
        # double the limit in a single step
        gradient = max(0.5, min(1.0, self.tolerance * long / short / 2.0 + 0.5))
        candidate = self._limit * gradient + math.sqrt(self._limit)
        self._limit = max(
            float(self.min_limit),
            min(float(self.max_limit),
                (1.0 - self.smoothing) * self._limit
                + self.smoothing * candidate))

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "mode": self.mode,
                "limit": round(self._limit, 2),
                "min_limit": self.min_limit,
                "max_limit": self.max_limit,
                "target_ms": self.target_ms,
                "minrtt_ms": (round(self._minrtt_s * 1e3, 3)
                              if self._minrtt_s is not None else None),
                "good_total": self.good_total,
                "breach_total": self.breach_total,
                "decay_total": self.decay_total,
            }


# waiter states; transitions happen ONLY under the controller lock
_WAITING = "waiting"
_ADMITTED = "admitted"
_CANCELLED = "cancelled"
_SHED = "shed"


class _Waiter:
    """One parked acquire: a sync thread (``event``) or an asyncio task
    (``loop`` + ``future``). ``state`` transitions only under the
    controller lock — the event/future is a wakeup hint, never the
    authority on who owns the admission slot."""

    __slots__ = ("lane", "rank", "tenant", "deadline", "enqueued_ns",
                 "state", "event", "loop", "future", "shed_reason")

    def __init__(self, lane: str, rank: int, deadline: Optional[float],
                 tenant: Optional[str] = None):
        self.lane = lane
        self.rank = rank
        self.tenant = tenant
        self.deadline = deadline
        self.enqueued_ns = time.perf_counter_ns()
        self.state = _WAITING
        self.event: Optional[threading.Event] = None
        self.loop = None
        self.future = None
        self.shed_reason: Optional[str] = None

    def notify(self) -> bool:
        """Wake the waiter; False when it can never wake (its event loop
        is closed) so the caller can reclaim the admission slot instead
        of leaking it — and instead of letting the RuntimeError abort
        the rest of a release's notify batch."""
        if self.event is not None:
            self.event.set()
            return True
        try:
            self.loop.call_soon_threadsafe(self._resolve)
            return True
        except RuntimeError:
            return False

    def _resolve(self) -> None:
        if not self.future.done():
            self.future.set_result(True)


class _TenantQueue:
    """One tenant's LIFO waiter stack within a lane, plus its WFQ
    virtual finish time. ``vtime`` advances by ``1/weight`` per admitted
    waiter; the drain always serves the smallest-vtime tenant next, so
    service converges to weight-proportional shares under contention.
    Mutations happen under the controller lock."""

    __slots__ = ("stack", "depth", "vtime", "weight")

    def __init__(self, weight: float):
        self.stack: deque = deque()
        self.depth = 0  # live (non-cancelled) waiters of this tenant
        self.vtime = 0.0
        self.weight = weight


class _Lane:
    """One priority lane: per-tenant LIFO waiter queues drained
    weighted-fair, plus the lane's counters. ``vclock`` is the lane's
    virtual clock — the vtime of the last served tenant; a tenant whose
    queue went idle re-enters at ``max(its vtime, vclock)`` so idling
    never banks catch-up credit (the classic WFQ start-time rule). With
    a single tenant the drain degenerates to the exact legacy
    LIFO-within-lane order. Mutations happen under the controller lock;
    cancelled waiters stay in their stack (marked) and are skipped
    lazily at drain time."""

    __slots__ = ("label", "rank", "queues", "depth", "vclock",
                 "admitted_total", "shed_by_reason")

    def __init__(self, label: str, rank: int):
        self.label = label
        self.rank = rank
        self.queues: Dict[Optional[str], _TenantQueue] = {}
        self.depth = 0  # live (non-cancelled) waiters across tenants
        self.vclock = 0.0
        self.admitted_total = 0
        self.shed_by_reason: Dict[str, int] = {}


class AdmissionToken:
    """One admitted request's slot. ``release`` returns the slot and
    feeds the limiter: pass the completion latency and whether the
    outcome was ok; ``latency_s=None`` with ``ok=True`` releases without
    feeding (nothing was learned). Double release raises."""

    __slots__ = ("_ctrl", "lane", "tenant", "waited_s", "_released")

    def __init__(self, ctrl: "AdmissionController", lane: str,
                 waited_s: float, tenant: Optional[str] = None):
        self._ctrl = ctrl
        self.lane = lane
        self.tenant = tenant
        self.waited_s = waited_s
        self._released = False

    def release(self, latency_s: Optional[float] = None,
                ok: bool = True) -> None:
        if self._released:
            raise InferenceServerException(
                "admission token released twice", status="ADMISSION_TOKEN")
        self._released = True
        self._ctrl._release(latency_s, ok, self.tenant)


class AdmissionController:
    """The pool-level admission gate: limiter + lanes + deadline shedding.

    ``acquire`` / ``acquire_async`` either return an
    :class:`AdmissionToken` (whose ``release`` MUST be called exactly
    once) or raise :class:`AdmissionRejected`. One token should cover one
    logical request end to end — the pool acquires before routing and
    releases after the whole failover/hedge engine finishes, so retries
    and hedges never multiply admission.

    ``observer`` (duck-typed, see ``observe.Telemetry.attach_admission``):
    ``on_admission_admit(lane, waited_s)`` / ``on_admission_shed(lane,
    reason)``, called outside the lock and never allowed to break the
    data path."""

    def __init__(
        self,
        limiter: Optional[AdaptiveLimiter] = None,
        mode: str = "aimd",
        target_ms: Optional[float] = None,
        max_queue: int = 64,
        max_queue_wait_s: float = 0.5,
        shed_low_when_saturated: bool = True,
        eta_factor: float = 1.0,
        lane_map: Callable[[int], Tuple[str, int]] = default_lane_map,
        tenancy: Optional[Any] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        """``limiter`` defaults to ``AdaptiveLimiter(mode=mode,
        target_ms=target_ms)``. ``max_queue`` bounds EACH lane's waiter
        stack; ``max_queue_wait_s`` bounds how long any waiter parks
        before it sheds (also clamped by the request's own deadline minus
        the limiter's service-time estimate). ``eta_factor`` scales the
        estimate in the deadline-feasibility test (>1 sheds earlier).
        ``tenancy`` — a ``client_tpu.tenancy.TenancyPolicy`` (or a spec
        string for ``parse_tenancy_spec``) arming per-tenant quotas and
        weighted-fair drain; None keeps the controller tenant-blind
        (tenants still get separate queues but equal weight and no
        quota)."""
        if max_queue < 0:
            raise ValueError("max_queue must be >= 0")
        if max_queue_wait_s < 0:
            raise ValueError("max_queue_wait_s must be >= 0")
        if isinstance(tenancy, str):
            from .tenancy import parse_tenancy_spec
            tenancy = parse_tenancy_spec(tenancy, clock=clock)
        self.tenancy = tenancy
        self.limiter = limiter or AdaptiveLimiter(
            mode=mode, target_ms=target_ms)
        self.max_queue = int(max_queue)
        self.max_queue_wait_s = float(max_queue_wait_s)
        self.shed_low_when_saturated = shed_low_when_saturated
        self.eta_factor = float(eta_factor)
        self._lane_map = lane_map
        self._clock = clock
        self._lock = threading.Lock()
        self._inflight = 0
        self._lanes: Dict[str, _Lane] = {}
        self.admitted_total = 0
        self.shed_total = 0
        self.observer = None

    # -- introspection --------------------------------------------------------
    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def queue_depths(self) -> Dict[str, int]:
        with self._lock:
            return {label: lane.depth for label, lane in self._lanes.items()}

    def snapshot(self) -> Dict[str, Any]:
        limiter = self.limiter.snapshot()
        with self._lock:
            lanes = {}
            for label, lane in self._lanes.items():
                row: Dict[str, Any] = {
                    "depth": lane.depth,
                    "admitted_total": lane.admitted_total,
                    "shed": dict(lane.shed_by_reason),
                }
                # per-tenant queue depths, only once a real (non-None)
                # tenant has queued here — tenantless snapshots stay
                # byte-identical to the pre-tenancy schema
                if any(t is not None for t in lane.queues):
                    row["tenants"] = {
                        (t if t is not None else "_default"): tq.depth
                        for t, tq in lane.queues.items()
                    }
                lanes[label] = row
            snap = {
                "limit": limiter["limit"],
                "inflight": self._inflight,
                "admitted_total": self.admitted_total,
                "shed_total": self.shed_total,
                # pinned at the floor: the collapse signal doctor's
                # admission_collapse anomaly keys off (alongside SLO burn)
                "collapsed": limiter["limit"] <= limiter["min_limit"],
                "lanes": lanes,
                "limiter": limiter,
            }
        if self.tenancy is not None:
            # outside the controller lock: the policy takes its own
            snap["tenancy"] = self.tenancy.snapshot()
        return snap

    def watch_gauges(self) -> Dict[str, Any]:
        """The watchtower's gauge-source contract: cumulative totals the
        tower differences per tick into a live shed rate, plus the
        instantaneous pressure gauges."""
        limiter = self.limiter.snapshot()
        with self._lock:
            return {
                "admitted_total": self.admitted_total,
                "shed_total": self.shed_total,
                "inflight": self._inflight,
                "limit": limiter["limit"],
                "collapsed": limiter["limit"] <= limiter["min_limit"],
            }

    # -- internals ------------------------------------------------------------
    def _lane(self, label: str, rank: int) -> _Lane:
        lane = self._lanes.get(label)
        if lane is None:
            lane = self._lanes[label] = _Lane(label, rank)
        return lane

    def _observe_admit(self, lane: str, waited_s: float,
                       tenant: Optional[str] = None) -> None:
        if tenant is not None:
            _flight.note("admission", "admit", lane=lane, tenant=tenant,
                         waited_ms=round(waited_s * 1e3, 3))
        else:
            _flight.note("admission", "admit", lane=lane,
                         waited_ms=round(waited_s * 1e3, 3))
        if self.tenancy is not None:
            self.tenancy.on_admit(tenant)
        if self.observer is not None:
            try:
                self.observer.on_admission_admit(lane, waited_s)
            except Exception:
                pass  # an observer must never break the data path

    def _shed(self, lane: _Lane, reason: str,
              retry_after_s: Optional[float] = None,
              tenant: Optional[str] = None) -> AdmissionRejected:
        """Count one shed and build (not raise) the typed rejection."""
        with self._lock:
            self.shed_total += 1
            lane.shed_by_reason[reason] = (
                lane.shed_by_reason.get(reason, 0) + 1)
        exc = AdmissionRejected(reason, lane.label,
                                retry_after_s=retry_after_s,
                                tenant=tenant)
        if tenant is not None:
            _flight.note("admission", "shed", reason=reason,
                         lane=lane.label, tenant=tenant)
        else:
            _flight.note("admission", "shed", reason=reason,
                         lane=lane.label)
        if self.tenancy is not None:
            self.tenancy.on_shed(tenant, reason)
        if self.observer is not None:
            try:
                self.observer.on_admission_shed(lane.label, reason)
                exc.counted = True
            except Exception:
                pass
        return exc

    def _deadline_infeasible(self, deadline: Optional[float],
                             now: float) -> bool:
        """Could this request still complete before its deadline if it
        were admitted right now? (minRTT EWMA as the service estimate —
        shedding work that cannot possibly finish is the cheapest
        capacity there is.)"""
        if deadline is None:
            return False
        eta = self.limiter.eta_s()
        if eta is None:
            return deadline <= now  # no estimate: only shed already-late
        return now + eta * self.eta_factor > deadline

    def _try_admit_locked(self, rank: int) -> bool:
        """Fast-path admission under the lock. A fresh arrival may take a
        free slot ahead of queued SAME-OR-LOWER-priority waiters (that IS
        the LIFO rule: the freshest request wins) but never ahead of a
        queued HIGHER-priority lane."""
        if self._inflight >= self.limiter.limit_int():
            return False
        for lane in self._lanes.values():
            if lane.depth > 0 and lane.rank < rank:
                return False
        self._inflight += 1
        return True

    def _tenant_queue_locked(self, lane: _Lane,
                             tenant: Optional[str]) -> _TenantQueue:
        tq = lane.queues.get(tenant)
        if tq is None:
            weight = (self.tenancy.weight(tenant)
                      if self.tenancy is not None else 1.0)
            tq = lane.queues[tenant] = _TenantQueue(weight)
        return tq

    def _park_locked(self, lane: _Lane, waiter: _Waiter) -> None:
        tq = self._tenant_queue_locked(lane, waiter.tenant)
        if tq.depth == 0:
            # the WFQ start-time rule: an idle tenant re-enters at the
            # lane's virtual clock, so idling never banks catch-up credit
            tq.vtime = max(tq.vtime, lane.vclock)
        tq.stack.append(waiter)
        tq.depth += 1
        lane.depth += 1

    def _drain_locked(self) -> List[_Waiter]:
        """Admit queued waiters while slots are free: lanes by rank
        (high first); within a lane, the tenant with the smallest virtual
        finish time drains next (weighted-fair — its vtime advances by
        ``1/weight`` per admit), NEWEST waiter first within the tenant.
        Waiters whose deadline became infeasible while parked are shed
        instead of admitted (their slot stays free, and the shed does not
        advance the tenant's vtime — no service was rendered). Returns
        waiters to notify OUTSIDE the lock."""
        to_notify: List[_Waiter] = []
        now = self._clock()
        lanes = sorted(self._lanes.values(), key=lambda l: l.rank)
        for lane in lanes:
            while lane.depth > 0 and self._inflight < self.limiter.limit_int():
                tq = min((q for q in lane.queues.values() if q.depth > 0),
                         key=lambda q: q.vtime)
                waiter = tq.stack.pop()  # LIFO: newest first
                if waiter.state != _WAITING:
                    continue  # cancelled: depths already decremented
                tq.depth -= 1
                lane.depth -= 1
                if self._deadline_infeasible(waiter.deadline, now):
                    waiter.state = _SHED
                    waiter.shed_reason = SHED_DEADLINE
                    to_notify.append(waiter)
                    continue
                waiter.state = _ADMITTED
                lane.vclock = max(lane.vclock, tq.vtime)
                tq.vtime += 1.0 / tq.weight
                self._inflight += 1
                lane.admitted_total += 1
                self.admitted_total += 1
                to_notify.append(waiter)
        return to_notify

    def _release(self, latency_s: Optional[float], ok: bool,
                 tenant: Optional[str] = None) -> None:
        self.limiter.on_result(latency_s, ok)
        if self.tenancy is not None and not (latency_s is None and ok):
            # neutral releases (no signal) skip the tenant's SLO window
            self.tenancy.on_result(tenant, latency_s, ok)
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
            to_notify = self._drain_locked()
        while to_notify:
            dead = [w for w in to_notify if not w.notify()]
            if not dead:
                return
            # a waiter whose loop died can never wake: reclaim any slot
            # transferred to it and hand the capacity to the next waiter
            with self._lock:
                for w in dead:
                    if w.state == _ADMITTED:
                        w.state = _CANCELLED
                        self._inflight = max(0, self._inflight - 1)
                to_notify = self._drain_locked()

    def _admit_or_park(self, priority: int, deadline: Optional[float],
                       loop=None, tenant: Optional[str] = None,
                       lane: Optional[Tuple[str, int]] = None) -> Any:
        """Shared front half of the sync/async acquire: fast-path admit
        (returns a token), immediate shed (raises), or a parked waiter
        (returned for the caller to wait on). One lock acquisition
        decides everything — a slot freed between two separate critical
        sections could otherwise strand a fresh waiter until timeout.
        ``loop`` non-None builds an asyncio waiter (future created BEFORE
        the waiter is published, so a racing wakeup always has something
        to notify). ``lane`` overrides the priority→lane mapping with an
        explicit ``(label, rank)`` — the disaggregated prefill/decode
        layer charges its two legs to separate lanes this way (their
        SLOs differ); lanes are created lazily, no registration needed."""
        label, rank = lane if lane is not None \
            else self._lane_map(priority or 0)
        # the quota gate runs FIRST and unconditionally — even on an idle
        # controller. A quota is policy, not a load response: an
        # over-quota tenant is denied whether or not capacity is free,
        # with the bucket's refill eta as the honest retry hint
        if self.tenancy is not None:
            quota_ok, refill_eta = self.tenancy.try_take(tenant)
            if not quota_ok:
                with self._lock:
                    lane = self._lane(label, rank)
                raise self._shed(lane, SHED_OVER_QUOTA,
                                 retry_after_s=refill_eta, tenant=tenant)
        # deadline feasibility is judged ONLY when saturated (below): an
        # idle controller always admits, even a request the minRTT EWMA
        # says is doomed — a wrong estimate then costs one admitted
        # request whose completion CORRECTS the estimate, whereas
        # shedding at the door would starve the estimator of completions
        # and lock a transiently-inflated minRTT into a permanent
        # full-shed outage
        infeasible = self._deadline_infeasible(deadline, self._clock())
        shed_reason: Optional[str] = None
        waiter: Optional[_Waiter] = None
        admitted = False
        with self._lock:
            lane = self._lane(label, rank)
            if self._try_admit_locked(rank):
                lane.admitted_total += 1
                self.admitted_total += 1
                admitted = True
            elif infeasible:
                shed_reason = SHED_DEADLINE
            elif self.shed_low_when_saturated and label == LANE_LOW:
                shed_reason = SHED_SATURATED
            elif (self.max_queue == 0
                  or self._tenant_queue_locked(lane, tenant).depth
                  >= self.max_queue):
                # the bound is per TENANT queue: one tenant's backlog
                # fills its own queue, never the whole lane's
                shed_reason = SHED_QUEUE_FULL
            else:
                waiter = _Waiter(label, rank, deadline, tenant)
                if loop is None:
                    waiter.event = threading.Event()
                else:
                    waiter.loop = loop
                    waiter.future = loop.create_future()
                self._park_locked(lane, waiter)
        if admitted:
            self._observe_admit(label, 0.0, tenant)
            return AdmissionToken(self, label, 0.0, tenant)
        if waiter is not None:
            return waiter
        raise self._shed(lane, shed_reason,
                         retry_after_s=self.limiter.eta_s(),
                         tenant=tenant)

    def _wait_bound_s(self, deadline: Optional[float]) -> float:
        """How long a waiter may park: the queue-wait cap, clamped so a
        deadline-carrying request leaves itself the limiter's service
        estimate to actually run."""
        bound = self.max_queue_wait_s
        if deadline is not None:
            eta = self.limiter.eta_s() or 0.0
            bound = min(bound, max(
                0.0, deadline - self._clock() - eta * self.eta_factor))
        return bound

    def _settle_waiter(self, waiter: _Waiter) -> Tuple[str, Optional[str]]:
        """Resolve a waiter's final state under the lock after its wait
        ended (wakeup, timeout or cancellation). Ownership is decided
        HERE: a wakeup racing a timeout may have admitted the waiter
        already — then the slot is ours and the timeout is moot."""
        with self._lock:
            state, reason = waiter.state, waiter.shed_reason
            if state == _WAITING:
                waiter.state = _CANCELLED
                lane = self._lanes[waiter.lane]
                lane.depth -= 1
                tq = lane.queues.get(waiter.tenant)
                if tq is not None:
                    tq.depth -= 1
                    # remove the tombstone NOW: drain pops newest-first,
                    # so a cancelled waiter buried under live ones would
                    # otherwise sit in the deque forever — unbounded
                    # growth exactly during the sustained saturation this
                    # module exists for
                    try:
                        tq.stack.remove(waiter)
                    except ValueError:
                        pass  # already popped (and skipped) by a drain
                return _CANCELLED, None
            return state, reason

    def _finish_wait(self, waiter: _Waiter) -> AdmissionToken:
        """Shared back half of the sync/async acquire: turn the settled
        waiter into a token or the right typed rejection."""
        state, reason = self._settle_waiter(waiter)
        lane = self._lanes[waiter.lane]
        if state == _ADMITTED:
            waited = (time.perf_counter_ns() - waiter.enqueued_ns) * 1e-9
            self._observe_admit(waiter.lane, waited, waiter.tenant)
            return AdmissionToken(self, waiter.lane, waited, waiter.tenant)
        if state == _SHED:
            raise self._shed(lane, reason or SHED_DEADLINE,
                             tenant=waiter.tenant)
        raise self._shed(lane, SHED_QUEUE_TIMEOUT,
                         retry_after_s=self.limiter.eta_s(),
                         tenant=waiter.tenant)

    def _force_admit(self, priority: int,
                     tenant: Optional[str] = None,
                     lane: Optional[Tuple[str, int]] = None) -> AdmissionToken:
        """Unconditional admission (still counted in-flight): established
        sequences use it — shedding step k of a sequence the server
        already holds state for would poison replica-local state, which
        is strictly worse than the overload it would relieve. The
        tenant's quota IS still charged (debt bounded at one burst), so
        a long sequence consumes quota without ever being shed."""
        label, rank = lane if lane is not None \
            else self._lane_map(priority or 0)
        if self.tenancy is not None:
            self.tenancy.charge(tenant)
        with self._lock:
            lane = self._lane(label, rank)
            self._inflight += 1
            lane.admitted_total += 1
            self.admitted_total += 1
        self._observe_admit(label, 0.0, tenant)
        return AdmissionToken(self, label, 0.0, tenant)

    # -- sync acquire ---------------------------------------------------------
    def acquire(self, priority: int = 0,
                deadline: Optional[float] = None,
                force: bool = False,
                tenant: Optional[str] = None,
                lane: Optional[Tuple[str, int]] = None) -> AdmissionToken:
        """Admit one request or raise :class:`AdmissionRejected`.
        ``deadline`` is an absolute ``time.monotonic`` instant (the
        request's budget), enabling deadline-aware shedding. ``force``
        admits unconditionally (never sheds, still counts in-flight).
        ``tenant`` selects the tenant's virtual queue and quota (None:
        the tenantless default queue). ``lane`` is an explicit
        ``(label, rank)`` override of the priority→lane mapping (lanes
        are created lazily): the disaggregated prefill/decode layer
        charges its legs to separate lanes whose SLOs differ."""
        if force:
            return self._force_admit(priority, tenant, lane=lane)
        parked = self._admit_or_park(priority, deadline, tenant=tenant,
                                     lane=lane)
        if isinstance(parked, AdmissionToken):
            return parked
        waiter: _Waiter = parked
        # unlocked depth read: a point-in-time queue-depth annotation on
        # the flight timeline, not an accounting source
        _flight.note("admission", "park", lane=waiter.lane,
                     depth=self._lanes[waiter.lane].depth)
        waiter.event.wait(self._wait_bound_s(deadline))
        return self._finish_wait(waiter)

    # -- async acquire --------------------------------------------------------
    async def acquire_async(self, priority: int = 0,
                            deadline: Optional[float] = None,
                            force: bool = False,
                            tenant: Optional[str] = None,
                            lane: Optional[Tuple[str, int]] = None,
                            ) -> AdmissionToken:
        """Asyncio twin of :meth:`acquire`. Cancellation mid-wait returns
        the slot if the wakeup raced the cancel — a cancelled caller can
        never leak admission."""
        import asyncio

        if force:
            return self._force_admit(priority, tenant, lane=lane)
        parked = self._admit_or_park(
            priority, deadline, loop=asyncio.get_running_loop(),
            tenant=tenant, lane=lane)
        if isinstance(parked, AdmissionToken):
            return parked
        waiter: _Waiter = parked
        _flight.note("admission", "park", lane=waiter.lane,
                     depth=self._lanes[waiter.lane].depth)
        try:
            await asyncio.wait_for(
                waiter.future, timeout=self._wait_bound_s(deadline))
        except asyncio.TimeoutError:
            pass  # _finish_wait decides ownership under the lock
        except asyncio.CancelledError:
            state, reason = self._settle_waiter(waiter)
            if state == _ADMITTED:
                # the wakeup won the race: give the slot back
                self._release(None, True, waiter.tenant)
            elif state == _SHED:
                # a drain shed this waiter just before the cancel landed:
                # the shed HAPPENED — count it (the built exception is
                # discarded; the caller sees its CancelledError)
                self._shed(self._lanes[waiter.lane],
                           reason or SHED_DEADLINE, tenant=waiter.tenant)
            raise
        return self._finish_wait(waiter)
