"""Client-side adaptive micro-batching: a coalescing infer dispatcher.

Every concurrent caller of ``infer()`` has, until now, paid full request
serialization and its own wire round-trip — even though the in-repo
server's ``DynamicBatcher`` happily executes stacked rows. This module
moves the batching decision to the CLIENT, where the aggregate arrival
stream is visible before it fans out into sockets: an opt-in wrapper (in
the style of ``client_tpu.pool.PoolClient``) that queues concurrent
``infer()`` calls per compatibility key, stacks them along the batch
dimension into ONE KServe request, sends it once, and scatters the result
rows back to each caller::

    from client_tpu.batch import BatchingClient

    client = BatchingClient("127.0.0.1:8000", protocol="http",
                            batch_max_rows=32)
    client.infer("batched_matmul", inputs)   # may ride a shared request

    # or wrap an existing client / pool (one coalesced request per
    # routing decision):
    client = PoolClient(urls, protocol="http").coalescing()

What coalesces, and what never does:

- **Compatibility key** — requests merge only when ``(model, version,
  per-input (name, dtype, shape[1:]), requested outputs, parameters,
  priority, timeouts, headers, compression)`` all agree. The key mirrors
  the server batcher's rule: merging across differing parameters would
  silently compute under the wrong ones.
- **Sequence requests NEVER coalesce** (``sequence_id != 0``): they carry
  server-side state transitions and are delegated verbatim to the inner
  client (which already pins/never-resends them).
- Shared-memory-bound tensors, JSON-staged (``binary_data=False``)
  tensors, per-request ``resilience=`` overrides, and requests already at
  or above ``batch_max_rows`` bypass to the inner client unchanged.

Dispatch mechanics (sync): leader/follower with zero extra threads. The
first caller into an idle queue becomes the *leader*: it waits out the
coalescing window (woken early when the queue reaches ``batch_max_rows``),
claims the queued calls, sends the stacked request, and scatters rows;
followers park on the queue's condition until their rows (or the batch's
typed error) arrive. Leadership hands off to a queued follower whenever a
claim leaves a remainder, so dispatches pipeline — a new batch can be
in-flight while the previous one is still on the wire. The asyncio twin
replaces the leader with a per-key flusher task and dispatches batches as
independent tasks.

**Adaptive window** — ``window_us=None`` (default) tunes the coalescing
window from EWMAs of the observed inter-arrival gap and wire service
time: the candidate window is ``gap * (batch_max_rows - 1)`` (just long
enough to fill a batch at the observed rate), capped at ``max_window_us``
AND at half the observed service time (so coalesced e2e latency stays
within ~1.5x while the batch size multiplies throughput); when the
candidate window would collect fewer than ~2 arrivals — a lone
closed-loop caller's gap IS the service time — the window is ZERO and
light traffic pays no added latency (a lone call is passed through
verbatim, original ``request_id`` included). The live window is exported
as the ``client_tpu_batch_window_us`` gauge.

Composition contract:

- **Under ``ResiliencePolicy``** — the dispatcher issues ONE inner
  ``infer``; the inner client's policy (retry/breaker) applies to the
  coalesced request, which is idempotent by construction (only
  non-sequence calls merge). A failed batch fans the SAME typed error out
  to every caller in it.
- **Behind ``PoolClient``** — wrap the pool: each coalesced request is one
  routing decision (one replica choice, one failover/hedge engine run) —
  and, with the pool's admission control armed (``client_tpu.admission``),
  ONE admission decision: a coalesced batch admits once, and a shed batch
  fans the same typed ``AdmissionRejected`` to every caller (counted as
  ``shed_dispatches`` in :meth:`stats`, distinct from dispatch errors).
  Requests with different ``priority`` values never share a key, so the
  admission controller's lanes still see each caller's true priority.
- **Telemetry** — with an ``observe.Telemetry`` configured (or adopted
  from the inner client), every caller gets its own ``RequestSpan`` with a
  ``coalesce_queue`` phase (enqueue -> claim) and an ``attempt`` phase
  (the shared wire call), plus the ``client_tpu_batch_rows`` batch-size
  histogram, dispatch/mode counters and the window gauge on ``/metrics``.

See docs/batching.md for the full interaction matrix.
"""

from __future__ import annotations

import asyncio
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from . import flight as _flight
from ._base import fold_infer_args
from ._tensor import InferInput
from .utils import InferenceServerException, sorted_percentile

__all__ = [
    "AioBatchingClient",
    "BatchingClient",
    "CoalescedInferResult",
    "plan_request",
]

# batch-size histogram edges (rows per dispatched wire request)
BATCH_ROWS_BUCKETS: Tuple[float, ...] = (
    1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64, 96, 128, 192, 256,
)

def plan_request(inputs, kwargs):
    """Shared eligibility + signature scan for the client-side wrapper
    layers — the coalescing dispatcher here and the response cache /
    singleflight collapser (``client_tpu.cache``) reuse ONE exclusion
    matrix, so "what may coalesce" and "what may collapse or cache" can
    never drift apart. (The model name is not scanned here — each layer
    folds it into its own key.)

    Returns ``(sig, rows, raw_by_name, out_sig, extra_key)`` when the
    request is a plain, binary-staged, stateless infer:

    - ``sig``: sorted ``((name, datatype, shape-tail), ...)`` per input
    - ``rows``: the shared leading (batch) dimension
    - ``raw_by_name``: each input's staged binary payload
    - ``out_sig``: sorted requested-output signature (None = all outputs)
    - ``extra_key``: a canonical repr of every other semantic kwarg

    Returns None when the request must bypass: sequences (server-side
    state transitions), per-request ``resilience=`` overrides, shm-bound
    or JSON-staged tensors, per-tensor parameters, ragged/absent batch
    dims, and classification or shm-placed outputs."""
    if kwargs.get("sequence_id"):
        return None  # sequence semantics: NEVER merged or cached
    if kwargs.get("resilience") is not None:
        return None  # per-request policy override: honor it verbatim
    if not inputs:
        return None
    sig: List[Tuple[str, str, Tuple[int, ...]]] = []
    raw_by_name: Dict[str, Any] = {}
    rows: Optional[int] = None
    try:
        for inp in inputs:
            raw = inp._get_binary_data()
            if raw is None:
                return None  # shm-bound or JSON-staged tensor
            if inp._parameters:
                return None  # per-tensor parameters don't stack
            shape = inp.shape()
            if not shape:
                return None
            r = int(shape[0])
            if r < 1:
                return None
            if rows is None:
                rows = r
            elif rows != r:
                return None  # ragged batch dims can't scatter back
            sig.append((inp.name(), inp.datatype(),
                        tuple(int(d) for d in shape[1:])))
            raw_by_name[inp.name()] = raw
    except AttributeError:
        return None  # not the shared InferInput value model
    if rows is None:
        return None
    outputs = kwargs.get("outputs")
    out_sig = None
    if outputs:
        out_entries = []
        try:
            for out in outputs:
                if out._in_shared_memory() or out._class_count:
                    return None
                out_entries.append((out.name(), bool(out._binary_data)))
        except AttributeError:
            return None
        out_sig = tuple(sorted(out_entries))
    extra = {
        k: v for k, v in kwargs.items()
        # request_id is caller bookkeeping; affinity_key is a ROUTING
        # hint the pool pops before the wire — requests differing only by
        # session key produce identical answers, so they may share a
        # batch row, a singleflight, and a cache entry (the dispatched
        # request carries the first caller's key).
        # tenant= is deliberately NOT excluded: folding it here is THE
        # cross-tenant isolation point — cache keys, singleflight groups
        # and coalesced batches all partition by tenant in this one
        # place, so tenant A can never be served (or collapse onto)
        # tenant B's response. Tenantless callers (tenant=None) fall
        # under the `v is not None` filter and keep byte-identical keys.
        if k not in ("request_id", "outputs", "resilience", "affinity_key")
        and v is not None
        and not (k in ("sequence_id", "sequence_start", "sequence_end",
                       "priority") and not v)
    }
    try:
        extra_key = repr(sorted(extra.items()))
    except Exception:
        return None
    return tuple(sorted(sig)), rows, raw_by_name, out_sig, extra_key


_EWMA_ALPHA = 0.2  # inter-arrival gap / service-time smoothing
# adaptive windows never exceed this fraction of the observed wire service
# time: a batch may wait at most half a round-trip, bounding the coalesced
# e2e latency to ~1.5x while the batch size multiplies throughput
_SERVICE_FRAC = 0.5
# a window is only worth opening when it is expected to collect at least
# this many arrivals (window / ewma_gap); below it, dispatch immediately
_MIN_EXPECTED_ARRIVALS = 1.5


class _PendingCall:
    """One caller's infer, queued for coalescing."""

    __slots__ = ("inputs", "sig", "raw", "kwargs", "rows", "span",
                 "enqueued_ns", "claimed", "done", "result", "error",
                 "future", "batch_rows", "batch_calls")

    def __init__(self, inputs, sig, raw, kwargs, rows, span):
        self.inputs = inputs      # the caller's original InferInput list
        self.sig = sig            # ((name, datatype, tail), ...) sorted
        self.raw = raw            # name -> staged binary payload
        self.kwargs = kwargs
        self.rows = rows
        self.span = span
        self.enqueued_ns = time.perf_counter_ns()
        self.claimed = False
        self.done = False
        self.result = None
        self.error: Optional[BaseException] = None
        self.future = None        # aio only
        # stamped at settle so the CALLER's thread/task can annotate its
        # own flight timeline with the batch it rode
        self.batch_rows = 0
        self.batch_calls = 0


class _SyncKeyState:
    """One compatibility key's queue (sync client). All mutable fields are
    guarded by ``cond``; ``leader`` is the call currently running the
    window/claim cycle (None between cycles)."""

    __slots__ = ("cond", "items", "rows", "leader", "model",
                 "last_arrival_ns", "ewma_gap_ns", "ewma_service_ns",
                 "window_us")

    def __init__(self, model: str):
        self.cond = threading.Condition()
        self.items: deque = deque()
        self.rows = 0
        self.leader = None
        self.model = model
        self.last_arrival_ns = 0
        self.ewma_gap_ns: Optional[float] = None
        self.ewma_service_ns: Optional[float] = None
        self.window_us = 0.0

    def busy(self) -> bool:
        return bool(self.items) or self.leader is not None


class _AioKeyState:
    """One compatibility key's queue (asyncio client; loop-confined, so no
    lock — mutations only happen between awaits)."""

    __slots__ = ("items", "rows", "task", "wake", "model",
                 "last_arrival_ns", "ewma_gap_ns", "ewma_service_ns",
                 "window_us")

    def __init__(self, model: str):
        self.items: deque = deque()
        self.rows = 0
        self.task: Optional[asyncio.Task] = None
        self.wake = asyncio.Event()
        self.model = model
        self.last_arrival_ns = 0
        self.ewma_gap_ns: Optional[float] = None
        self.ewma_service_ns: Optional[float] = None
        self.window_us = 0.0

    def busy(self) -> bool:
        return bool(self.items) or self.task is not None


class _SharedBatchResult:
    """The decoded view of one coalesced response, shared by every
    caller's row slice: each output tensor is decoded ONCE (on first
    access, under a lock) no matter how many callers slice it."""

    __slots__ = ("result", "total_rows", "_lock", "_arrays")

    def __init__(self, result: Any, total_rows: int):
        self.result = result
        self.total_rows = total_rows
        self._lock = threading.Lock()
        self._arrays: Dict[str, Any] = {}

    def array(self, name: str):
        with self._lock:
            if name not in self._arrays:
                arr = self.result.as_numpy(name)
                if arr is not None and (
                        arr.ndim == 0 or arr.shape[0] != self.total_rows):
                    raise InferenceServerException(
                        f"coalesced output '{name}' has shape "
                        f"{getattr(arr, 'shape', None)}; expected leading "
                        f"dimension {self.total_rows}",
                        status="COALESCE_SCATTER")
                self._arrays[name] = arr
            return self._arrays[name]


class CoalescedInferResult:
    """One caller's row slice of a coalesced response.

    Quacks like the frontends' ``InferResult``: ``as_numpy`` returns a
    zero-copy view of this caller's rows, ``get_output``/``get_response``
    rewrite shapes to the slice, and transport extras (e.g.
    ``get_response_header``) delegate to the underlying batch result.
    ``batch_result()`` is the escape hatch to the full response."""

    __slots__ = ("_shared", "_start", "_stop")

    def __init__(self, shared: _SharedBatchResult, start: int, stop: int):
        self._shared = shared
        self._start = start
        self._stop = stop

    def as_numpy(self, name: str):
        arr = self._shared.array(name)
        if arr is None:
            return None
        return arr[self._start:self._stop]

    def as_jax(self, name: str, device=None):
        arr = self.as_numpy(name)
        if arr is None:
            return None
        import numpy as np

        if arr.dtype == np.object_:
            raise InferenceServerException(
                "BYTES outputs cannot be placed on device")
        import jax

        return jax.device_put(arr, device)

    def get_output(self, name: str) -> Optional[Dict[str, Any]]:
        out = self._shared.result.get_output(name)
        if out is None:
            return None
        out = dict(out)
        shape = list(out.get("shape") or ())
        if shape:
            shape[0] = self._stop - self._start
            out["shape"] = shape
        params = out.get("parameters")
        if params:
            # per-batch byte counts don't describe the slice
            params = {k: v for k, v in params.items()
                      if k != "binary_data_size"}
            if params:
                out["parameters"] = params
            else:
                out.pop("parameters", None)
        return out

    def get_response(self) -> Dict[str, Any]:
        resp = dict(self._shared.result.get_response())
        outputs = []
        for out in resp.get("outputs", []) or []:
            sliced = self.get_output(out.get("name"))
            if sliced is not None:
                outputs.append(sliced)
        resp["outputs"] = outputs
        resp.pop("raw_output_contents", None)  # grpc: rows live in as_numpy
        return resp

    def get_response_header(self, name: str, default=None):
        getter = getattr(self._shared.result, "get_response_header", None)
        if getter is None:
            return default
        return getter(name, default)

    def batch_result(self):
        """The undivided transport result the whole batch shares."""
        return self._shared.result


class _BatchingCore:
    """Construction, eligibility, key/queue bookkeeping, stacking, scatter
    and accounting shared by the sync and asyncio wrappers."""

    _AIO = False
    _MAX_STATES = 512  # idle-key pruning threshold

    def __init__(
        self,
        client,
        protocol: str = "http",
        window_us: Optional[float] = None,
        max_window_us: float = 20000.0,
        batch_max_rows: int = 32,
        telemetry=None,
    ):
        """``client``: an existing frontend/pool client to wrap, or a
        ``host:port`` url (built with ``protocol``, sync or aio to match
        this wrapper; ``close()`` closes the inner client either way).
        ``window_us``: fixed coalescing window in microseconds; ``None``
        (default) auto-tunes from the observed arrival rate, capped at
        ``max_window_us``. ``batch_max_rows`` bounds the stacked batch
        dimension — size it to the serving model's ``max_batch_size``.
        ``telemetry``: an ``observe.Telemetry``; when omitted, the inner
        client's configured telemetry is adopted."""
        if batch_max_rows < 1:
            raise ValueError("batch_max_rows must be >= 1")
        if window_us is not None and window_us < 0:
            raise ValueError("window_us must be >= 0")
        if max_window_us <= 0:
            raise ValueError("max_window_us must be > 0")
        if isinstance(client, str):
            from .pool import _default_client_factory

            client = _default_client_factory(protocol, self._AIO)(client)
        self._inner = client
        self.window_us = window_us
        self.max_window_us = float(max_window_us)
        self.batch_max_rows = int(batch_max_rows)
        self._frontend = f"{getattr(client, '_FRONTEND', 'client')}+batch"
        self._states: Dict[Any, Any] = {}
        self._states_lock = threading.Lock()
        self._closed = False
        # running stats (always on; cheap slots + a bounded deque)
        self._stats_lock = threading.Lock()
        self._dispatches = 0
        self._coalesced = 0
        self._solo = 0
        self._bypass = 0
        self._dispatch_errors = 0
        self._shed_dispatches = 0
        self._recent_rows: deque = deque(maxlen=4096)
        self._last_window_us = 0.0
        # telemetry instruments: one (rows, dispatch, calls, errors,
        # window) tuple swapped atomically so a concurrent dispatch reads
        # all five or none (configure_telemetry may run mid-traffic)
        self._telemetry = None
        self._instruments = None
        if telemetry is None:
            accessor = getattr(client, "telemetry", None)
            if callable(accessor):
                try:
                    telemetry = accessor()
                except Exception:
                    telemetry = None
        if telemetry is not None:
            self.configure_telemetry(telemetry)

    # -- configuration -------------------------------------------------------
    def configure_telemetry(self, telemetry):
        """Install (or clear) the telemetry this dispatcher reports into:
        per-caller spans with a ``coalesce_queue`` phase, the batch-size
        histogram, dispatch/mode counters and the window gauge. The inner
        client's own telemetry (tracing the wire request) is configured
        separately on the inner client."""
        self._telemetry = telemetry
        if telemetry is None:
            self._instruments = None
            return self
        reg = telemetry.registry
        self._instruments = (
            reg.histogram(
                "client_tpu_batch_rows",
                "Rows per dispatched (possibly coalesced) infer request",
                ("model",), buckets=BATCH_ROWS_BUCKETS),
            reg.counter(
                "client_tpu_batch_dispatch_total",
                "Wire requests issued by the coalescing dispatcher",
                ("model",)),
            reg.counter(
                "client_tpu_batch_calls_total",
                "Caller-level infers by dispatch mode",
                ("model", "mode")),
            reg.counter(
                "client_tpu_batch_errors_total",
                "Dispatched batches that failed (error fanned out to every "
                "caller)", ("model",)),
            reg.gauge(
                "client_tpu_batch_window_us",
                "Live coalescing window per model (auto-tuned unless "
                "window_us is fixed)", ("model",)),
        )
        return self

    def telemetry(self):
        return self._telemetry

    def configure_resilience(self, policy):
        """Resilience belongs to the inner client: the coalesced request
        runs under whatever policy the wrapped client (or pool) carries."""
        return self._inner.configure_resilience(policy)

    def configure_arena(self, arena):
        """The shm arena belongs to the inner client too: arena-leased
        (shm-param) inputs bypass coalescing verbatim, while plain binary
        inputs coalesce and the JOINED batch payload is promoted into one
        leased slab at dispatch — zero-copy batching end to end. Returns
        this wrapper (not the inner client) so configuration chains."""
        self._inner.configure_arena(arena)
        return self

    def arena(self):
        return self._inner.arena()

    def stats(self) -> Dict[str, Any]:
        """A snapshot of dispatcher behavior: dispatch/solo/coalesced/
        bypass counts, the live window, and batch-size percentiles over
        the most recent dispatches."""
        with self._stats_lock:
            rows = sorted(self._recent_rows)
            return {
                "dispatches": self._dispatches,
                "coalesced_calls": self._coalesced,
                "solo_calls": self._solo,
                "bypass_calls": self._bypass,
                "dispatch_errors": self._dispatch_errors,
                "shed_dispatches": self._shed_dispatches,
                "window_us": round(self._last_window_us, 1),
                "batch_rows": {
                    "p50": sorted_percentile(rows, 0.5),
                    "p99": sorted_percentile(rows, 0.99),
                    "max": rows[-1] if rows else 0,
                    "mean": round(sum(rows) / len(rows), 2) if rows else 0.0,
                },
            }

    # -- eligibility / compatibility key -------------------------------------
    def _plan(self, model_name: str, inputs, kwargs):
        """``(key, rows, raw_by_name, sig)`` when this call may coalesce,
        else None (bypass to the inner client unchanged). Eligibility and
        signatures come from the shared :func:`plan_request` scan."""
        plan = plan_request(inputs, kwargs)
        if plan is None:
            return None
        sig_t, rows, raw_by_name, out_sig, extra_key = plan
        if rows >= self.batch_max_rows:
            return None  # already a full batch: nothing to gain by queueing
        key = (model_name, sig_t, out_sig, extra_key)
        return key, rows, raw_by_name, sig_t

    def _new_state(self, model: str):
        raise NotImplementedError

    def _state_for(self, key, model: str):
        with self._states_lock:
            state = self._states.get(key)
            if state is None:
                if len(self._states) >= self._MAX_STATES:
                    for k in [k for k, s in self._states.items()
                              if not s.busy()]:
                        del self._states[k]
                state = self._new_state(model)
                self._states[key] = state
            return state

    # -- adaptive window ------------------------------------------------------
    def _note_arrival(self, state) -> None:
        now = time.perf_counter_ns()
        last = state.last_arrival_ns
        state.last_arrival_ns = now
        if last:
            gap = float(now - last)
            ewma = state.ewma_gap_ns
            state.ewma_gap_ns = (
                gap if ewma is None else ewma + _EWMA_ALPHA * (gap - ewma))

    def _window_s(self, state) -> float:
        if self.window_us is not None:
            window_us = self.window_us
        else:
            # the window worth waiting: long enough to fill a batch at the
            # observed arrival rate, but never more than max_window_us nor
            # half the observed service time (so the coalesced e2e stays
            # within ~1.5x while the batch size multiplies throughput)
            gap_ns = state.ewma_gap_ns
            window_us = 0.0
            if gap_ns is not None and gap_ns > 0.0:
                target_ns = gap_ns * (self.batch_max_rows - 1)
                cap_ns = self.max_window_us * 1e3
                service_ns = state.ewma_service_ns
                if service_ns is not None:
                    cap_ns = min(cap_ns, service_ns * _SERVICE_FRAC)
                target_ns = min(target_ns, cap_ns)
                # light traffic: a window expecting fewer than ~2 arrivals
                # (a lone closed-loop caller's gap IS the service time)
                # only adds latency — dispatch immediately instead
                if target_ns / gap_ns >= _MIN_EXPECTED_ARRIVALS:
                    window_us = target_ns / 1e3
        state.window_us = window_us
        self._last_window_us = window_us
        return window_us / 1e6

    @staticmethod
    def _note_service(state, wire_ns: int) -> None:
        ewma = state.ewma_service_ns
        state.ewma_service_ns = (
            float(wire_ns) if ewma is None
            else ewma + _EWMA_ALPHA * (wire_ns - ewma))

    # -- claiming / stacking / scatter ----------------------------------------
    def _claim(self, state) -> List[_PendingCall]:
        """Pop a batch (FIFO, up to ``batch_max_rows`` rows) off the
        queue. The head is always taken even when oversized — it cannot
        be split."""
        cap = self.batch_max_rows
        items = state.items
        batch: List[_PendingCall] = []
        rows = 0
        while items:
            nxt = items[0]
            if batch and rows + nxt.rows > cap:
                break
            items.popleft()
            nxt.claimed = True
            batch.append(nxt)
            rows += nxt.rows
            if rows >= cap:
                break
        state.rows -= rows
        return batch

    def _stack(self, batch: List[_PendingCall]):
        """One stacked request for the whole batch: per-input payloads are
        concatenated along axis 0 (raw row-major bytes concatenate
        directly — this holds for fixed-width dtypes, BF16 and the
        length-prefixed BYTES wire format alike), and the shared kwargs
        are the key-identical first caller's minus its request_id."""
        first = batch[0]
        total = sum(c.rows for c in batch)
        inputs = []
        for name, datatype, tail in first.sig:
            inp = InferInput(name, [total, *tail], datatype)
            inp._raw_data = b"".join(c.raw[name] for c in batch)
            inputs.append(inp)
        kwargs = dict(first.kwargs)
        kwargs.pop("request_id", None)
        return inputs, kwargs, total

    @staticmethod
    def _check_batch_shapes(result, total_rows: int) -> None:
        """Cheap pre-scatter validation off the response header: every
        output must carry ``total_rows`` leading rows, or the mismatch is
        fanned out as a typed error instead of mis-sliced data."""
        for out in result.get_response().get("outputs", []) or []:
            shape = out.get("shape") or []
            if not shape or int(shape[0]) != total_rows:
                raise InferenceServerException(
                    f"coalesced response output {out.get('name')!r} has "
                    f"shape {list(shape)}; expected leading dimension "
                    f"{total_rows}", status="COALESCE_SCATTER")

    def _scatter(self, parent, batch: List[_PendingCall], total_rows: int):
        shared = _SharedBatchResult(parent, total_rows)
        offset = 0
        for call in batch:
            call.result = CoalescedInferResult(
                shared, offset, offset + call.rows)
            offset += call.rows

    # -- accounting -----------------------------------------------------------
    def _count_bypass(self, model: str) -> None:
        with self._stats_lock:
            self._bypass += 1
        instruments = self._instruments
        if instruments is not None:
            instruments[2].labels(model, "bypass").inc()

    @staticmethod
    def _is_shed(error: Optional[BaseException]) -> bool:
        """Was this dispatch shed by admission control?"""
        from .admission import ADMISSION_REJECTED_STATUS

        return (isinstance(error, InferenceServerException)
                and error.status() == ADMISSION_REJECTED_STATUS)

    def _account_dispatch(self, state, batch: List[_PendingCall],
                          total_rows: int, error: bool,
                          shed: bool = False) -> None:
        n = len(batch)
        with self._stats_lock:
            self._dispatches += 1
            self._recent_rows.append(total_rows)
            if n == 1:
                self._solo += 1
            else:
                self._coalesced += n
            if shed:
                # a shed batch is honest load-shedding, not a dispatch
                # failure — accounted separately so error_rate math stays
                # truthful under overload
                self._shed_dispatches += 1
            elif error:
                self._dispatch_errors += 1
        instruments = self._instruments
        if instruments is not None:
            m_rows, m_dispatch, m_calls, m_errors, m_window = instruments
            model = state.model
            m_rows.labels(model).observe(total_rows)
            m_dispatch.labels(model).inc()
            m_calls.labels(model, "solo" if n == 1 else "coalesced").inc(n)
            if error and not shed:
                m_errors.labels(model).inc()
            m_window.labels(model).set(round(state.window_us, 1))

    def _finish_spans(self, batch: List[_PendingCall], t_wire0: int,
                      t_wire1: int, total_rows: int,
                      error: Optional[BaseException]) -> None:
        tel = self._telemetry
        if tel is None:
            return
        for call in batch:
            span = call.span
            if span is None:
                continue
            span.phase("coalesce_queue", call.enqueued_ns, t_wire0)
            span.phase("attempt", t_wire0, t_wire1)
            span.event("coalesced", rows=call.rows, batch_rows=total_rows,
                       batch_calls=len(batch))
            tel.finish(span, error=error)

    def _begin_span(self, model: str):
        tel = self._telemetry
        if tel is None:
            return None
        return tel.begin(self._frontend, model)

    # -- composition -----------------------------------------------------------
    def caching(self, **kwargs):
        """Wrap THIS batching client in the hot-key layer (cache outside
        batching: hits skip the coalescing window, misses may still ride
        a batch). Without this override ``__getattr__`` would delegate to
        the inner client and silently compose the cache around the POOL
        instead — dropping the batching layer from the chain."""
        from .cache import AioCachingClient, CachingClient

        cls = AioCachingClient if self._AIO else CachingClient
        return cls(self, **kwargs)

    # -- generic surface delegation -------------------------------------------
    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._inner, name)


class BatchingClient(_BatchingCore):
    """Synchronous coalescing wrapper over any sync frontend or pool.

    ``infer`` runs the dispatcher; every other method is delegated to the
    inner client untouched."""

    _AIO = False

    def _new_state(self, model: str) -> _SyncKeyState:
        return _SyncKeyState(model)

    # -- lifecycle -----------------------------------------------------------
    def close(self) -> None:
        self._closed = True
        self._inner.close()

    def __enter__(self) -> "BatchingClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- inference -------------------------------------------------------------
    def infer(self, model_name: str, inputs, *args, **kwargs):
        """Coalescing ``infer`` (drop-in: positional arguments follow the
        frontends' shared prefix). Sequence requests, shm/JSON tensors and
        per-request resilience overrides bypass to the inner client; a
        lone eligible call is passed through verbatim (zero rewrite)."""
        kwargs = fold_infer_args(args, kwargs)
        # materialize first: _plan iterates inputs, and a generator would
        # reach the inner client (or the passthrough) exhausted
        inputs = list(inputs) if inputs is not None else inputs
        plan = self._plan(model_name, inputs, kwargs)
        if plan is None:
            self._count_bypass(model_name)
            return self._inner.infer(model_name, inputs, **kwargs)
        key, rows, raw, sig = plan
        call = _PendingCall(inputs, sig, raw, kwargs, rows,
                            self._begin_span(model_name))
        scratch = _flight.layer_begin(self._telemetry, "batch", model_name)
        _flight.note("batch", "join", rows=rows)
        if scratch is None:
            return self._infer_queued(model_name, key, call)
        try:
            result = self._infer_queued(model_name, key, call)
        except BaseException as e:
            _flight.layer_commit(self._telemetry, scratch, error=e)
            raise
        _flight.layer_commit(self._telemetry, scratch)
        return result

    def _infer_queued(self, model_name: str, key, call: _PendingCall):
        """The queue/lead/follow engine behind :meth:`infer` (split out so
        the flight-recorder wrapper above owns one scratch per caller)."""
        state = self._state_for(key, model_name)
        with state.cond:
            self._note_arrival(state)
            state.items.append(call)
            state.rows += call.rows
            if (state.leader is not None
                    and state.rows >= self.batch_max_rows):
                state.cond.notify_all()  # wake the leader: batch is full
        while True:
            batch = None
            with state.cond:
                while not call.done:
                    if state.leader is None and not call.claimed:
                        state.leader = call
                        batch = self._lead_locked(state)
                        break
                    state.cond.wait()
                if call.done:
                    break
            # leader duty continues OUTSIDE the lock: the wire call must
            # not serialize new arrivals (they queue for the next leader)
            self._dispatch(state, batch)
            # the claimed batch may not include this call (row-cap
            # overflow): loop back to follow — or lead — again
        _flight.note("batch", "dispatched", rows=call.rows,
                     batch_rows=call.batch_rows,
                     batch_calls=call.batch_calls)
        if call.error is not None:
            raise call.error
        return call.result

    # -- leader duty ----------------------------------------------------------
    def _lead_locked(self, state: _SyncKeyState) -> List[_PendingCall]:
        """Wait out the coalescing window (cut short when the queue
        reaches the row cap), then claim the batch and hand leadership
        off. Caller holds ``state.cond``."""
        cap = self.batch_max_rows
        window_s = self._window_s(state)
        if window_s > 0.0 and state.rows < cap:
            deadline = time.monotonic() + window_s
            while state.rows < cap:
                remaining = deadline - time.monotonic()
                if remaining <= 0.0:
                    break
                state.cond.wait(remaining)
        batch = self._claim(state)
        state.leader = None
        state.cond.notify_all()  # a queued follower takes the next cycle
        return batch

    def _dispatch(self, state: _SyncKeyState,
                  batch: List[_PendingCall]) -> None:
        if not batch:
            return
        t0 = time.perf_counter_ns()
        total_rows = sum(c.rows for c in batch)
        error: Optional[BaseException] = None
        try:
            if len(batch) == 1:
                # verbatim passthrough: identical to an uncoalesced call
                call = batch[0]
                call.result = self._inner.infer(
                    state.model, call.inputs, **call.kwargs)
            else:
                inputs, kwargs, total_rows = self._stack(batch)
                parent = self._inner.infer(state.model, inputs, **kwargs)
                self._check_batch_shapes(parent, total_rows)
                self._scatter(parent, batch, total_rows)
        except BaseException as e:
            error = e
        t1 = time.perf_counter_ns()
        # unblock the parked followers FIRST: accounting/span bookkeeping
        # must never sit between a caller and its result (nor, if it ever
        # misbehaved, strand the batch)
        self._settle(state, batch, error)
        if error is None:
            self._note_service(state, t1 - t0)
        self._account_dispatch(state, batch, total_rows,
                               error=error is not None,
                               shed=self._is_shed(error))
        self._finish_spans(batch, t0, t1, total_rows, error)
        if error is not None and not isinstance(error, Exception):
            raise error  # KeyboardInterrupt/SystemExit: don't swallow

    def _settle(self, state: _SyncKeyState, batch: List[_PendingCall],
                error: Optional[BaseException]) -> None:
        total_rows = sum(c.rows for c in batch)
        n = len(batch)
        with state.cond:
            for call in batch:
                call.batch_rows = total_rows
                call.batch_calls = n
                call.error = error
                call.done = True
            state.cond.notify_all()


class AioBatchingClient(_BatchingCore):
    """Asyncio twin of :class:`BatchingClient` over the aio frontends (or
    an ``AioPoolClient``). A per-key flusher task replaces the leader;
    batches dispatch as independent tasks so they pipeline."""

    _AIO = True

    def __init__(self, client, **kwargs):
        super().__init__(client, **kwargs)
        self._dispatch_tasks: set = set()

    def _new_state(self, model: str) -> _AioKeyState:
        return _AioKeyState(model)

    # -- lifecycle -----------------------------------------------------------
    async def close(self) -> None:
        self._closed = True
        closed_exc = InferenceServerException(
            "batching client closed", status="499")
        for state in list(self._states.values()):
            if state.task is not None:
                state.task.cancel()
            while state.items:
                call = state.items.popleft()
                state.rows -= call.rows
                if call.future is not None and not call.future.done():
                    call.future.set_exception(closed_exc)
        if self._dispatch_tasks:
            await asyncio.gather(
                *list(self._dispatch_tasks), return_exceptions=True)
        result = self._inner.close()
        if asyncio.iscoroutine(result):
            await result

    async def __aenter__(self) -> "AioBatchingClient":
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    # -- inference -------------------------------------------------------------
    async def infer(self, model_name: str, inputs, *args, **kwargs):
        """Coalescing async ``infer`` (same eligibility/bypass contract as
        the sync twin)."""
        kwargs = fold_infer_args(args, kwargs)
        # materialize first (see the sync twin): _plan iterates inputs
        inputs = list(inputs) if inputs is not None else inputs
        plan = self._plan(model_name, inputs, kwargs)
        if plan is None or self._closed:
            self._count_bypass(model_name)
            return await self._inner.infer(model_name, inputs, **kwargs)
        key, rows, raw, sig = plan
        call = _PendingCall(inputs, sig, raw, kwargs, rows,
                            self._begin_span(model_name))
        call.future = asyncio.get_running_loop().create_future()
        scratch = _flight.layer_begin(self._telemetry, "batch", model_name)
        _flight.note("batch", "join", rows=rows)
        state = self._state_for(key, model_name)
        self._note_arrival(state)
        state.items.append(call)
        state.rows += call.rows
        if state.task is None:
            state.task = asyncio.ensure_future(self._flush_loop(state))
        elif state.rows >= self.batch_max_rows:
            state.wake.set()  # cut the window short: batch is full
        if scratch is None:
            return await call.future
        try:
            result = await call.future
        except BaseException as e:
            _flight.note("batch", "dispatched", rows=call.rows,
                         batch_rows=call.batch_rows,
                         batch_calls=call.batch_calls)
            _flight.layer_commit(self._telemetry, scratch, error=e)
            raise
        _flight.note("batch", "dispatched", rows=call.rows,
                     batch_rows=call.batch_rows,
                     batch_calls=call.batch_calls)
        _flight.layer_commit(self._telemetry, scratch)
        return result

    # -- flusher --------------------------------------------------------------
    async def _flush_loop(self, state: _AioKeyState) -> None:
        try:
            while state.items:
                window_s = self._window_s(state)
                if window_s > 0.0 and state.rows < self.batch_max_rows:
                    state.wake.clear()
                    try:
                        await asyncio.wait_for(state.wake.wait(), window_s)
                    except asyncio.TimeoutError:
                        pass
                batch = self._claim(state)
                if not batch:
                    break
                # dispatch as its own task: the flusher keeps claiming
                # while previous batches are still on the wire
                task = asyncio.ensure_future(self._dispatch(state, batch))
                self._dispatch_tasks.add(task)
                task.add_done_callback(self._dispatch_tasks.discard)
        finally:
            # reset synchronously with the final items-check: arrivals only
            # run between awaits, so none can slip in unflushed
            state.task = None

    async def _dispatch(self, state: _AioKeyState,
                        batch: List[_PendingCall]) -> None:
        t0 = time.perf_counter_ns()
        total_rows = sum(c.rows for c in batch)
        error: Optional[BaseException] = None
        try:
            if len(batch) == 1:
                call = batch[0]
                call.result = await self._inner.infer(
                    state.model, call.inputs, **call.kwargs)
            else:
                inputs, kwargs, total_rows = self._stack(batch)
                parent = await self._inner.infer(
                    state.model, inputs, **kwargs)
                self._check_batch_shapes(parent, total_rows)
                self._scatter(parent, batch, total_rows)
        except BaseException as e:
            error = e
        t1 = time.perf_counter_ns()
        # settle the callers first (see the sync twin)
        n = len(batch)
        for call in batch:
            call.batch_rows = total_rows
            call.batch_calls = n
            if call.future is None or call.future.done():
                continue  # cancelled caller: nothing to deliver
            if error is not None:
                call.future.set_exception(error)
            else:
                call.future.set_result(call.result)
        if error is None:
            self._note_service(state, t1 - t0)
        self._account_dispatch(state, batch, total_rows,
                               error=error is not None,
                               shed=self._is_shed(error))
        self._finish_spans(batch, t0, t1, total_rows, error)
        if error is not None and not isinstance(error, Exception):
            raise error
