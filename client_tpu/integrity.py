"""End-to-end response integrity: contract validation for every InferResult.

Every robustness layer below this one (resilience, pools, federation,
disagg) defends against endpoints that are *slow or dead*; this module
defends against endpoints that are *wrong*. A replica that lies about
shapes or dtypes, truncates a binary tensor, mis-frames a BYTES payload,
echoes the wrong request id, or replays a duplicate stream index must
surface as a typed :class:`IntegrityError` — never as a garbage numpy
view handed to the caller.

Three layers, wired through ``_base`` into all four frontends:

* **Contract validation** (default ON): every ``InferResult`` is checked
  against the request before it reaches the caller — returned output
  names vs the requested set, datatype/shape vs cached model metadata,
  binary payload sizes vs the header's claims and the shape x dtype
  arithmetic, BYTES length-framing walked to exact exhaustion, and the
  ``request_id`` echo. Validation is pure arithmetic over data already
  in memory: zero extra RPCs, nanoseconds per call (the bench's A/A arm
  proves the overhead sits inside the noise floor).
* **Stream index checks** (opt-in): SSE / decoupled stream events that
  carry an index must be strictly monotone within one wire stream — no
  duplicates, no gaps. Opt-in because recovery layers (e.g.
  ``disagg``'s re-prefill) legitimately dedup verified replays ACROSS
  re-opened streams and own that stronger semantic check themselves.
* **Data-plane digests** (opt-in, ``arena.LeaseDigest``): blake2b-128
  over shm/arena-resident outputs, sealed when the response lands and
  re-verified at ``as_numpy()`` map time, so a server that scribbles
  over a slab AFTER answering is caught before the first read. Digest
  state rides the existing lease: steady state stays 0 extra RPCs.

Classification: :class:`IntegrityError` carries the
``INTEGRITY_VIOLATION`` status, which ``resilience.classify_fault`` maps
to the ``INVALID`` fault domain — never retried on the SAME endpoint
(it answered; it answered wrong), failed over for idempotent requests,
and counted into the pool's quarantine window (N invalid responses
inside the window ejects the endpoint with a typed
``EndpointQuarantined`` pool event).

Fundamental limit, stated honestly: a bit-flip INSIDE a fixed-width
payload whose sizes all agree is invisible to any client-side check
without redundancy. The contract layer catches every *structural* lie;
value-level corruption is covered where redundancy exists (BYTES
framing, arena digests, disagg's token continuity) — see
docs/integrity.md for the full detection matrix.
"""

from __future__ import annotations

import struct
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import flight as _flight
from .utils import InferenceServerException, triton_to_np_dtype

__all__ = [
    "INTEGRITY_VIOLATION_STATUS",
    "IntegrityError",
    "IntegrityPolicy",
    "IntegrityStats",
    "StreamChecker",
    "default_policy",
    "element_size",
    "expected_nbytes",
    "global_stats",
    "note_parse_violation",
    "validate_result",
    "walk_bytes_framing",
]

INTEGRITY_VIOLATION_STATUS = "INTEGRITY_VIOLATION"


class IntegrityError(InferenceServerException):
    """A response failed contract validation.

    ``kind`` names the violated check (``output_name`` / ``dtype`` /
    ``shape`` / ``payload_size`` / ``tail`` / ``bytes_framing`` /
    ``request_id`` / ``stream_index`` / ``digest``), ``url`` the
    answering endpoint (may be empty for a bare client), ``field`` the
    offending output/field, and ``expected``/``actual`` the mismatched
    values. Carries the ``INTEGRITY_VIOLATION`` status so
    ``resilience.classify_fault`` maps it to the INVALID domain.
    """

    def __init__(self, kind: str, url: str, field: str,
                 expected: Any, actual: Any):
        super().__init__(
            f"integrity violation [{kind}] from {url or '<endpoint>'}: "
            f"{field!r} expected {expected!r}, got {actual!r}",
            status=INTEGRITY_VIOLATION_STATUS)
        self.kind = kind
        self.url = url
        self.field = field
        self.expected = expected
        self.actual = actual


# -- byte arithmetic ----------------------------------------------------------

# BF16 has no numpy dtype through triton_to_np_dtype on every install;
# its wire format is always 2 bytes/element little-endian
_BF16_ITEMSIZE = 2


def element_size(datatype: str) -> Optional[int]:
    """Wire bytes per element for a fixed-width Triton datatype; None for
    BYTES (length-framed) and unknown datatypes."""
    if datatype == "BYTES":
        return None
    if datatype == "BF16":
        return _BF16_ITEMSIZE
    np_dtype = triton_to_np_dtype(datatype)
    if np_dtype is None:
        return None
    return np.dtype(np_dtype).itemsize


def expected_nbytes(datatype: str, shape: Sequence[int]) -> Optional[int]:
    """shape x dtype wire size for fixed-width datatypes; None when the
    size is not statically computable (BYTES / unknown dtype)."""
    item = element_size(datatype)
    if item is None:
        return None
    n = 1
    for dim in shape:
        if not isinstance(dim, int) or isinstance(dim, bool) or dim < 0:
            return None
        n *= dim
    return n * item


def walk_bytes_framing(buf, count: int, url: str, field: str) -> int:
    """Walk a BYTES tensor's 4-byte length framing to EXACT exhaustion.

    Exactly ``count`` elements must consume exactly ``len(buf)`` bytes;
    a truncated prefix, an element running past the buffer, too few
    elements, or trailing slack all raise a typed ``bytes_framing``
    :class:`IntegrityError` (never an unhandled struct error)."""
    view = memoryview(buf)
    total = len(view)
    offset = 0
    for index in range(count):
        if offset + 4 > total:
            raise IntegrityError(
                "bytes_framing", url, field,
                f"length prefix for element {index}",
                f"buffer exhausted at byte {offset}/{total}")
        (length,) = struct.unpack_from("<I", view, offset)
        offset += 4
        if offset + length > total:
            raise IntegrityError(
                "bytes_framing", url, field,
                f"{length} bytes for element {index}",
                f"{total - offset} bytes remaining")
        offset += length
    if offset != total:
        raise IntegrityError(
            "bytes_framing", url, field,
            f"exactly {offset} framed bytes for {count} elements",
            f"{total} bytes ({total - offset} trailing)")
    return offset


# -- cumulative accounting ----------------------------------------------------

class IntegrityStats:
    """Thread-safe counters + a bounded overhead reservoir.

    One process-wide instance (:func:`global_stats`) backs the doctor's
    ``--integrity`` section and ``perf.py --validate``'s
    ``client_integrity`` row block; violations are additionally keyed by
    (kind, url) so a byzantine replica is NAMEABLE from the counters
    alone."""

    _RESERVOIR = 4096  # overhead samples kept for p50/p99 (ring)

    def __init__(self):
        self._lock = threading.Lock()
        self.checks = 0
        self.results = 0
        self.violations = 0
        self.violations_by_kind: Dict[str, int] = {}
        self.violations_by_url: Dict[str, int] = {}
        self._overhead_ns: List[int] = []
        self._overhead_pos = 0

    def record_checked(self, checks: int, overhead_ns: int) -> None:
        with self._lock:
            self.results += 1
            self.checks += checks
            if len(self._overhead_ns) < self._RESERVOIR:
                self._overhead_ns.append(overhead_ns)
            else:
                self._overhead_ns[self._overhead_pos] = overhead_ns
                self._overhead_pos = (self._overhead_pos + 1) % self._RESERVOIR
    def record_violation(self, kind: str, url: str) -> None:
        with self._lock:
            self.violations += 1
            self.violations_by_kind[kind] = (
                self.violations_by_kind.get(kind, 0) + 1)
            key = url or "<endpoint>"
            self.violations_by_url[key] = (
                self.violations_by_url.get(key, 0) + 1)

    def overhead_ns(self) -> Dict[str, Optional[float]]:
        with self._lock:
            samples = sorted(self._overhead_ns)
        if not samples:
            return {"p50": None, "p99": None, "samples": 0}
        def pct(q: float) -> float:
            idx = min(len(samples) - 1, int(q * (len(samples) - 1)))
            return float(samples[idx])
        return {"p50": pct(0.50), "p99": pct(0.99),
                "samples": len(samples)}

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            out = {
                "results": self.results,
                "checks": self.checks,
                "violations": self.violations,
                "violations_by_kind": dict(self.violations_by_kind),
                "violations_by_url": dict(self.violations_by_url),
            }
        out["overhead_ns"] = self.overhead_ns()
        return out


_GLOBAL_STATS = IntegrityStats()


def global_stats() -> IntegrityStats:
    """The process-wide stats instance every policy folds into by
    default (doctor / perf read exactly this)."""
    return _GLOBAL_STATS


# -- policy -------------------------------------------------------------------

class IntegrityPolicy:
    """What to check, and the (cached) model metadata to check against.

    ``contract`` (default True) arms the structural checks on every
    unary result. ``digests`` opts shm/arena-resident outputs into
    ``arena.LeaseDigest`` sealing at response-finish time (verified at
    map time). ``stream_index`` opts SSE/decoupled streams into the
    strict per-stream index monotonicity check (see module docstring
    for why recovery layers keep this off).

    Metadata is NEVER fetched by the validator (zero extra RPCs): it is
    captured for free when the owning client fetches
    ``get_model_metadata`` (``_base`` calls :meth:`note_metadata`), or
    primed explicitly by a harness. One policy may be shared across
    clients — a pool's endpoints then validate against one fleet-wide
    contract, which is exactly what catches a single replica that
    disagrees with it.
    """

    def __init__(self, contract: bool = True, digests: bool = False,
                 stream_index: bool = False,
                 stats: Optional[IntegrityStats] = None):
        self.contract = contract
        self.digests = digests
        self.stream_index = stream_index
        self.stats = stats if stats is not None else _GLOBAL_STATS
        self._metadata_lock = threading.Lock()
        # model -> {output_name: (datatype, shape tuple or None)}
        self._metadata: Dict[str, Dict[str, Tuple[str, Optional[Tuple[int, ...]]]]] = {}

    # -- metadata cache ------------------------------------------------------
    def note_metadata(self, model_name: str, metadata: Any) -> None:
        """Fold a v2 model-metadata response (dict or object with
        ``.get``) into the contract cache. Malformed metadata is ignored
        — the cache only ever narrows what a response may claim."""
        try:
            outputs = metadata.get("outputs") or []
            table: Dict[str, Tuple[str, Optional[Tuple[int, ...]]]] = {}
            for out in outputs:
                name = out.get("name")
                datatype = out.get("datatype")
                if not isinstance(name, str) or not isinstance(datatype, str):
                    continue
                shape = out.get("shape")
                dims: Optional[Tuple[int, ...]] = None
                if isinstance(shape, (list, tuple)):
                    dims = tuple(int(d) for d in shape)
                table[name] = (datatype, dims)
        except Exception:
            return
        if table:
            with self._metadata_lock:
                self._metadata[model_name] = table

    def metadata_for(self, model_name: str) -> Optional[
            Dict[str, Tuple[str, Optional[Tuple[int, ...]]]]]:
        with self._metadata_lock:
            return self._metadata.get(model_name)


_DEFAULT_POLICY = IntegrityPolicy()


def default_policy() -> IntegrityPolicy:
    """The always-on process default every client validates under
    unless ``configure_integrity`` armed its own policy."""
    return _DEFAULT_POLICY


# -- stream checking ----------------------------------------------------------

# event keys accepted as the stream index (first match wins): the
# in-repo decode models emit ``INDEX``; generic decoupled responses may
# carry ``index`` / ``sequence_index``
_INDEX_KEYS = ("INDEX", "index", "sequence_index")


def event_index(event: Any) -> Optional[int]:
    """The stream index an SSE/decoupled event carries, or None."""
    if not isinstance(event, dict):
        return None
    for key in _INDEX_KEYS:
        value = event.get(key)
        if value is None:
            continue
        if isinstance(value, list):
            value = value[0] if value else None
        try:
            return int(value)
        except (TypeError, ValueError):
            return None
    return None


class StreamChecker:
    """Strict per-wire-stream index monotonicity: each indexed event
    must carry exactly ``previous + 1`` (the first indexed event pins
    the base). Duplicates, gaps and regressions raise a typed
    ``stream_index`` :class:`IntegrityError`; index-less events pass
    through uncounted."""

    __slots__ = ("url", "policy", "_next", "events")

    def __init__(self, url: str = "", policy: Optional[IntegrityPolicy] = None):
        self.url = url
        self.policy = policy if policy is not None else _DEFAULT_POLICY
        self._next: Optional[int] = None
        self.events = 0

    def observe(self, event: Any) -> Any:
        """Check one event; returns it unchanged for pipeline use."""
        index = event_index(event)
        if index is None:
            return event
        self.events += 1
        if self._next is not None and index != self._next:
            kind_expected = self._next
            self.policy.stats.record_violation("stream_index", self.url)
            _flight.note("integrity", "violation", kind="stream_index",
                         url=self.url, expected=kind_expected, actual=index)
            raise IntegrityError(
                "stream_index", self.url, "index", kind_expected, index)
        self._next = index + 1
        return event


# -- unary contract validation ------------------------------------------------

def _request_contract(inputs, outputs, request_id: str) -> Tuple[
        Optional[set], str, set]:
    """(requested output-name set or None when the server chooses,
    request id, class-mode output names) — extracted once per call.

    ``class_count`` outputs opt into the classification extension: the
    server REWRITES them to BYTES ``"value:idx:label"`` tensors of shape
    [class_count], so the cached metadata contract (the model's declared
    dtype/shape) deliberately does not apply to them."""
    requested: Optional[set] = None
    class_mode: set = set()
    if outputs:
        requested = set()
        for out in outputs:
            name = out.name() if callable(getattr(out, "name", None)) \
                else getattr(out, "name", "")
            requested.add(name)
            if getattr(out, "_class_count", 0):
                class_mode.add(name)
    return requested, request_id or "", class_mode


def _check_http_binary_tail(result, response: Dict[str, Any], url: str,
                            checks: List[int]) -> None:
    """HTTP only: the binary tail must be EXACTLY the sum of the header's
    binary_data_size claims — a response with trailing bytes nobody
    claimed (or an offsets map that under-consumes) is corrupt even when
    every per-output size is internally plausible."""
    buffer = getattr(result, "_buffer", None)
    offsets = getattr(result, "_offsets", None)
    if buffer is None or offsets is None:
        return
    checks[0] += 1
    binary_start = getattr(result, "_binary_start", len(buffer))
    claimed = sum(end - start for start, end in offsets.values())
    tail = len(buffer) - binary_start
    if claimed != tail:
        raise IntegrityError(
            "tail", url, "binary_tail",
            f"{claimed} claimed bytes", f"{tail} body bytes")


def _validate_output_entry(out: Dict[str, Any], url: str,
                           metadata, requested: Optional[set],
                           payload_nbytes: Optional[int],
                           payload, checks: List[int]) -> None:
    """Shared per-output checks over one response entry.

    ``payload_nbytes`` is the binary byte count the transport actually
    carries for this output (None when the output rode JSON data or a
    shared-memory region); ``payload`` is the raw buffer when available
    (BYTES framing is walked over it)."""
    name = out.get("name")
    if not isinstance(name, str) or not name:
        raise IntegrityError(
            "output_name", url, "name", "a named output", name)
    datatype = out.get("datatype", "")
    shape = out.get("shape", [])
    checks[0] += 1
    if requested is not None and name not in requested:
        raise IntegrityError(
            "output_name", url, name, sorted(requested), name)
    if not isinstance(shape, list) or any(
            (not isinstance(d, int)) or isinstance(d, bool) or d < 0
            for d in shape):
        raise IntegrityError("shape", url, name, "non-negative dims", shape)
    if metadata is not None:
        expected = metadata.get(name)
        if expected is not None:
            meta_dtype, meta_shape = expected
            checks[0] += 1
            if datatype != meta_dtype:
                raise IntegrityError(
                    "dtype", url, name, meta_dtype, datatype)
            if meta_shape is not None:
                # metadata dims: -1 is a free (batch/dynamic) axis; a
                # fixed axis must match exactly, and so must the rank
                checks[0] += 1
                if len(shape) != len(meta_shape):
                    raise IntegrityError(
                        "shape", url, name, list(meta_shape), shape)
                for got, want in zip(shape, meta_shape):
                    if want >= 0 and got != want:
                        raise IntegrityError(
                            "shape", url, name, list(meta_shape), shape)
    if payload_nbytes is None:
        return
    want = expected_nbytes(datatype, shape)
    if want is not None:
        checks[0] += 1
        if payload_nbytes != want:
            raise IntegrityError(
                "payload_size", url, name,
                f"{want} bytes for {datatype}{shape}",
                f"{payload_nbytes} bytes")
    elif datatype == "BYTES" and payload is not None:
        n_elems = 1
        for dim in shape:
            n_elems *= dim
        checks[0] += 1
        walk_bytes_framing(payload, n_elems, url, name)
    elif element_size(datatype) is None and datatype != "BYTES":
        raise IntegrityError(
            "dtype", url, name, "a known v2 datatype", datatype)


def _validate_http(result, url: str, metadata, requested: Optional[set],
                   checks: List[int]) -> None:
    response = result.get_response()
    _check_http_binary_tail(result, response, url, checks)
    offsets = getattr(result, "_offsets", {})
    buffer = getattr(result, "_buffer", b"")
    for out in response.get("outputs", []):
        name = out.get("name")
        params = out.get("parameters", {}) or {}
        payload_nbytes = None
        payload = None
        if isinstance(name, str) and name in offsets:
            start, end = offsets[name]
            payload_nbytes = end - start
            payload = buffer[start:end]
        elif "shared_memory_region" in params or "data" in out:
            payload_nbytes = None  # region- or JSON-resident
        _validate_output_entry(
            out, url, metadata, requested, payload_nbytes, payload, checks)


def _validate_grpc(result, url: str, metadata, requested: Optional[set],
                   checks: List[int]) -> None:
    response = result.get_response()
    raw = response.get("raw_output_contents", []) or []
    outputs = response.get("outputs", []) or []
    non_shm = [
        out for out in outputs
        if "shared_memory_region" not in (out.get("parameters") or {})
        and not out.get("contents")
    ]
    # raw_output_contents aligns with non-shm outputs IN ORDER: a short
    # or long raw list silently misaligns every later tensor
    if raw:
        checks[0] += 1
        if len(raw) != len(non_shm):
            raise IntegrityError(
                "tail", url, "raw_output_contents",
                f"{len(non_shm)} chunks", f"{len(raw)} chunks")
    raw_index = 0
    for out in outputs:
        params = out.get("parameters") or {}
        payload_nbytes = None
        payload = None
        if ("shared_memory_region" not in params
                and not out.get("contents")):
            if raw_index < len(raw):
                payload = raw[raw_index]
                payload_nbytes = len(payload)
            raw_index += 1
        _validate_output_entry(
            out, url, metadata, requested, payload_nbytes, payload, checks)


def validate_result(result, inputs=None, outputs=None, request_id: str = "",
                    url: str = "", model_name: str = "",
                    policy: Optional[IntegrityPolicy] = None) -> int:
    """Validate one unary ``InferResult`` against its request contract.

    Dispatches on the result's wire shape (HTTP byte-tail vs GRPC
    raw_output_contents), raising :class:`IntegrityError` on the first
    violation; returns the number of checks performed. The caller (the
    frontends' ``_integrity_check``) owns accounting and flight events.
    """
    active = policy if policy is not None else _DEFAULT_POLICY
    checks = [0]
    requested, want_id, class_mode = _request_contract(
        inputs, outputs, request_id)
    response = result.get_response()
    if want_id:
        checks[0] += 1
        got_id = response.get("id", "")
        if got_id != want_id:
            raise IntegrityError("request_id", url, "id", want_id, got_id)
    if requested is not None:
        checks[0] += 1
        got_names = [out.get("name")
                     for out in response.get("outputs", []) or []]
        missing = requested - set(got_names)
        if missing:
            raise IntegrityError(
                "output_name", url, ",".join(sorted(missing)),
                sorted(requested), sorted(n for n in got_names
                                          if isinstance(n, str)))
        if len(got_names) != len(set(got_names)):
            raise IntegrityError(
                "output_name", url, "outputs",
                "unique output names", got_names)
    metadata = active.metadata_for(model_name) if model_name else None
    if metadata and class_mode:
        # classification-extension outputs are rewritten server-side to
        # BYTES [class_count] tensors — the model's declared contract
        # does not describe them
        metadata = {k: v for k, v in metadata.items() if k not in class_mode}
    if hasattr(result, "_offsets"):
        _validate_http(result, url, metadata, requested, checks)
    else:
        _validate_grpc(result, url, metadata, requested, checks)
    return checks[0]


def check_result(result, inputs=None, outputs=None, request_id: str = "",
                 url: str = "", model_name: str = "",
                 policy: Optional[IntegrityPolicy] = None,
                 telemetry=None) -> None:
    """The frontends' one-call wrapper: validate + account.

    Times the validation, folds (checks, overhead) into the policy's
    stats, bumps the telemetry counters when a Telemetry is attached,
    and emits the ``integrity`` flight event on violation before
    re-raising."""
    active = policy if policy is not None else _DEFAULT_POLICY
    if not active.contract:
        return
    t0 = time.perf_counter_ns()
    try:
        checks = validate_result(
            result, inputs, outputs, request_id, url, model_name, active)
    except IntegrityError as e:
        active.stats.record_violation(e.kind, url)
        _flight.note("integrity", "violation", kind=e.kind, url=url,
                     field=e.field)
        if telemetry is not None:
            try:
                telemetry.integrity_violation(e.kind, url)
            except Exception:
                pass
        raise
    overhead = time.perf_counter_ns() - t0
    active.stats.record_checked(checks, overhead)
    if telemetry is not None:
        try:
            telemetry.integrity_checked("contract", url, checks)
        except Exception:
            pass


def note_parse_violation(err: IntegrityError, url: str = "",
                         telemetry=None,
                         policy: Optional[IntegrityPolicy] = None) -> None:
    """Attribute and account a parse-time :class:`IntegrityError`.

    Some violations (torn JSON header, binary sizes that overrun the
    body) are caught while *decoding* the response, before
    ``check_result`` ever runs — the decoder can't build a result object
    to validate. Decoders raise with ``url=""``; the frontend calls this
    to stamp its endpoint url on and fold the violation into the same
    stats / flight / telemetry streams, so a byzantine replica's torn
    responses count toward its quarantine exactly like contract lies.
    Parse violations are recorded even when contract checking is OFF: an
    undecodable body yields no result either way — the policy only
    chooses whether we *look* for lies, not whether torn bytes parse."""
    if url and not err.url:
        err.url = url
    active = policy if policy is not None else _DEFAULT_POLICY
    active.stats.record_violation(err.kind, err.url)
    _flight.note("integrity", "violation", kind=err.kind, url=err.url,
                 field=err.field)
    if telemetry is not None:
        try:
            telemetry.integrity_violation(err.kind, err.url)
        except Exception:
            pass
