"""``python -m client_tpu.doctor`` — a one-command fleet snapshot.

Answers "what is the fleet doing right now" in one shot: endpoint
health and breaker states, SLO status and burn rates, windowed TTFT/ITL
sketches, batch-dispatcher stats, the shm inventory and data-plane
accounting, per-endpoint ORCA load, a client/server/network latency
decomposition from a small probe load, and a clock-skew estimate from
trace joins — emitted as a human-readable summary plus a JSON artifact,
with anomaly flags (breaker open, SLO breach, shm churn above threshold,
load/latency divergence, clock skew, admission collapse). When the
passed telemetry carries attached admission controllers
(``PoolClient(admission=...)``), the snapshot gains an ``admission``
section (limit/inflight/per-lane sheds) and an ``admission_collapse``
anomaly fires when a limit is pinned at its floor while an SLO burns.

CLI::

    python -m client_tpu.doctor 127.0.0.1:8000 127.0.0.1:8001 \
        --protocol http --model simple --json doctor.json

Library::

    from client_tpu.doctor import collect_snapshot, render_summary
    snap = collect_snapshot(["127.0.0.1:8000"], telemetry=my_telemetry)

When an existing :class:`~client_tpu.observe.Telemetry` is passed, its
declared SLOs, stream windows and batch instruments are reported; the CLI
builds a fresh one (so those sections reflect only the probe run).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from . import observe
from .observe import StatsCorrelator, Telemetry
from .pool import EndpointSpec, PoolClient
from .utils import InferenceServerException, sorted_percentile, triton_to_np_dtype

__all__ = ["collect_snapshot", "postmortem_bundle", "render_summary",
           "main"]


def _input_module(protocol: str):
    if protocol == "http":
        import client_tpu.http as mod
    elif protocol == "grpc":
        import client_tpu.grpc as mod
    else:
        raise ValueError(f"unknown protocol {protocol!r} (http|grpc)")
    return mod


def _bounded_client_factory(protocol: str,
                            timeout_s: float) -> Callable[[str], Any]:
    """Doctor clients with every transport call bounded by the probe
    timeout: a replica that accepts TCP but never answers (the blackhole
    fault) must cost one timeout per call, not the transport's 60 s
    default times every snapshot RPC. HTTP bounds at the connection
    pool; gRPC calls carry per-call deadlines (see _bounded_call)."""
    mod = _input_module(protocol)
    if protocol == "http":
        return lambda url: mod.InferenceServerClient(
            url, connection_timeout=timeout_s, network_timeout=timeout_s)
    return lambda url: mod.InferenceServerClient(url)


def _bounded_call(fn: Callable, *args, timeout_s: float, **kwargs) -> Any:
    """Call a transport method with ``client_timeout=`` when it takes one
    (gRPC); HTTP methods are already bounded by the factory's pool
    timeouts."""
    if observe.accepts_client_timeout(fn):
        return fn(*args, client_timeout=timeout_s, **kwargs)
    return fn(*args, **kwargs)


def _synth_inputs(mod, metadata: Dict[str, Any]) -> List[Any]:
    """Build one InferInput per declared model input, with dynamic (-1)
    dims collapsed to 1 and deterministic fill data — enough to drive a
    representative probe infer against any served model."""
    inputs = []
    for spec in metadata.get("inputs", []):
        shape = [1 if int(d) < 0 else int(d) for d in spec.get("shape", [])]
        datatype = spec.get("datatype", "FP32")
        inp = mod.InferInput(spec.get("name", ""), shape, datatype)
        n = int(np.prod(shape)) if shape else 1
        if datatype == "BYTES":
            data = np.array([b"doctor"] * n, dtype=np.object_).reshape(shape)
        else:
            np_dtype = np.dtype(triton_to_np_dtype(datatype))
            data = np.ones(n, dtype=np_dtype).reshape(shape)
        inp.set_data_from_numpy(data)
        inputs.append(inp)
    return inputs


def _probe_endpoint(ep, mod, model: str, requests: int,
                    timeout_s: float) -> Dict[str, Any]:
    """Health-probe one endpoint, then drive ``requests`` probe infers on
    its client (telemetry + ORCA ride along automatically). The LAST
    infer is wall-clock bracketed for the skew estimate."""
    out: Dict[str, Any] = {"url": ep.url}
    try:
        out["live"] = bool(ep.client.is_server_live(
            probe=True, client_timeout=timeout_s))
        out["ready"] = bool(ep.client.is_server_ready(
            probe=True, client_timeout=timeout_s))
    except InferenceServerException as e:
        out["live"] = out["ready"] = False
        out["health_error"] = str(e)[:200]
    if not out["ready"]:
        return out
    try:
        metadata = _bounded_call(ep.client.get_model_metadata, model,
                                 timeout_s=timeout_s)
        inputs = _synth_inputs(mod, metadata)
    except Exception as e:
        out["probe_error"] = f"metadata: {e}"[:200]
        return out
    latencies: List[float] = []
    errors = 0
    skew_id = f"doctor-skew-{ep.url}"
    wall_t0 = wall_t1 = None
    for i in range(max(requests, 1)):
        last = i == max(requests, 1) - 1
        t0 = time.perf_counter()
        if last:
            wall_t0 = time.time()
        try:
            ep.client.infer(model, inputs, client_timeout=timeout_s,
                            request_id=skew_id if last else f"doctor-{i}")
        except Exception as e:
            errors += 1
            out.setdefault("probe_error", str(e)[:200])
            continue
        if last:
            wall_t1 = time.time()
        latencies.append(time.perf_counter() - t0)
    out["probe_requests"] = len(latencies)
    out["probe_errors"] = errors
    if latencies:
        ordered = sorted(latencies)
        out["probe_latency_ms"] = {
            "avg": round(sum(ordered) / len(ordered) * 1e3, 3),
            "p50": round(sorted_percentile(ordered, 0.5) * 1e3, 3),
            "max": round(ordered[-1] * 1e3, 3),
        }
    # -- clock skew from the trace join (HTTP transports expose the
    # access records at /v2/trace/access; wall_time_s is stamped at the
    # server's end of handling, so the client-side bracket bounds it)
    if wall_t0 is not None and wall_t1 is not None:
        record = _find_access_record(ep.client, skew_id)
        if record is not None and "wall_time_s" in record:
            midpoint = (wall_t0 + wall_t1) / 2.0
            out["clock_skew_ms"] = round(
                (record["wall_time_s"] - midpoint) * 1e3, 3)
            out["clock_skew_uncertainty_ms"] = round(
                (wall_t1 - wall_t0) / 2.0 * 1e3, 3)
            out["server_span"] = {
                "queue_ns": record.get("queue_ns"),
                "compute_ns": record.get("compute_ns"),
                "total_ns": record.get("total_ns"),
            }
    return out


def _find_access_record(client, request_id: str) -> Optional[Dict[str, Any]]:
    get = getattr(client, "_get", None)  # sync HTTP transport only
    if get is None:
        return None
    try:
        resp = get("v2/trace/access")
        if resp.status != 200:
            return None
        records = json.loads(resp.data)
    except Exception:
        return None
    for record in reversed(records):
        if record.get("request_id") == request_id:
            return record
    return None


def _server_shm_status(client, timeout_s: float) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for family, getter in (
            ("system", "get_system_shared_memory_status"),
            ("tpu", "get_tpu_shared_memory_status")):
        try:
            out[family] = _bounded_call(getattr(client, getter),
                                        timeout_s=timeout_s)
        except Exception as e:
            out[family] = {"error": str(e)[:200]}
    return out


def _total_dataplane_ops(dp: Dict[str, Any]) -> float:
    """Every lifecycle op + registration RPC in one recorder snapshot."""
    total = 0.0
    for fam in dp.get("families", {}).values():
        total += (fam["created"] + fam["attached"] + fam["map_reads"]
                  + fam["map_writes"] + fam["destroyed"])
    total += sum(dp.get("rpcs", {}).values())
    return total


def _local_shm(recorder) -> Dict[str, Any]:
    from .utils import shared_memory, tpu_shared_memory

    inventory = (shared_memory.region_inventory()
                 + tpu_shared_memory.region_inventory())
    return {
        "local_inventory": inventory,
        "dataplane": recorder.snapshot() if recorder is not None else None,
        "arena": _arena_status(),
    }


def _arena_status() -> List[Dict[str, Any]]:
    """One row per live ShmArena: slab/byte residency, hit rates, and the
    registration cache grouped per endpoint (empty list = no arenas)."""
    import sys

    arena_mod = sys.modules.get("client_tpu.arena")
    if arena_mod is None:
        return []
    rows = []
    for a in arena_mod.arenas():
        try:
            rows.append({
                "stats": a.stats(),
                "regions": a.inventory(),
                "registration_cache": a.registration_entries(),
            })
        except Exception as e:
            rows.append({"error": str(e)[:200]})
    return rows


def _arena_leased_bytes() -> int:
    """Total leased bytes across every live arena (leak-flag baseline)."""
    import sys

    arena_mod = sys.modules.get("client_tpu.arena")
    if arena_mod is None:
        return 0
    total = 0
    for a in arena_mod.arenas():
        try:
            total += a.stats()["leased_bytes"]
        except Exception:
            pass
    return total


def _cache_status() -> List[Dict[str, Any]]:
    """One row per live response cache (``client_tpu.cache``): hit rate,
    resident bytes, evictions by reason. Empty when the process never
    loaded the cache layer — lazy, like the arena section."""
    import sys

    cache_mod = sys.modules.get("client_tpu.cache")
    if cache_mod is None:
        return []
    rows = []
    for c in cache_mod.caches():
        try:
            rows.append(c.stats())
        except Exception as e:
            rows.append({"error": str(e)[:200]})
    return rows


def _tenancy_status() -> List[Dict[str, Any]]:
    """One row per live tenancy policy (``client_tpu.tenancy``): per-tenant
    admitted/shed totals, quota token level, SLO burn window and the
    noisy-neighbor verdicts. Empty when the process never loaded the
    tenancy layer — lazy, like the cache section."""
    import sys

    tenancy_mod = sys.modules.get("client_tpu.tenancy")
    if tenancy_mod is None:
        return []
    rows = []
    for policy in tenancy_mod.policies():
        try:
            rows.append(policy.snapshot())
        except Exception as e:
            rows.append({"error": str(e)[:200]})
    return rows


def _flight_status(tel: Telemetry) -> Optional[Dict[str, Any]]:
    """The flight-recorder section: retention accounting, the rolling
    tail-divergence verdict, and the newest anomalous timelines in
    summary form (trace id, verdict, duration, dominant attribution) —
    full timelines ship in the ``--postmortem`` bundle, not the
    snapshot."""
    recorder = getattr(tel, "flight", None)
    if recorder is None:
        return None
    anomalies = []
    for row in recorder.last_anomalies(8):
        anomalies.append({
            "trace_id": row["trace_id"],
            "verdict": row["verdict"],
            "model": row["model"],
            "duration_ms": row["duration_ms"],
            "error": row["error"],
            "events": len(row["events"]),
            "dominant": row["attribution"]["dominant"],
        })
    return {
        "stats": recorder.stats(),
        "tail_divergence": recorder.tail_divergence(),
        "last_anomalies": anomalies,
    }


def _federation_status(tel: Telemetry) -> List[Dict[str, Any]]:
    """One row per federation attached to the telemetry (the federation
    wires itself in at construction): per-cell role/health/breaker/spill
    state plus the shadow and canary views. Empty when no multi-cell
    client is armed."""
    rows = []
    for fed, scope in getattr(tel, "federations", lambda: [])():
        try:
            row = dict(fed.federation_stats())
        except Exception as e:
            row = {"error": str(e)[:200]}
        row["scope"] = scope
        rows.append(row)
    return rows


def _admission_status(tel: Telemetry) -> List[Dict[str, Any]]:
    """One row per admission controller attached to the telemetry (the
    pool wires its controller in at construction): limit, inflight,
    per-lane queue depth and shed counts. Empty when nothing is armed."""
    rows = []
    for ctrl, scope in tel.admission_controllers():
        try:
            row = dict(ctrl.snapshot())
        except Exception as e:
            row = {"error": str(e)[:200]}
        row["scope"] = scope
        rows.append(row)
    return rows


def _slo_status(tel: Telemetry) -> List[Dict[str, Any]]:
    return [
        {
            "name": slo.name,
            "metric": slo.metric,
            "threshold_ms": slo.threshold_ms,
            "objective": slo.objective,
            "window_s": slo.window_s,
            "burn_rate": round(slo.burn_rate(), 4),
            "breached": slo.breached(),
        }
        for slo in tel.slos()
    ]


def _shard_section(layout, snap: Dict[str, Any]) -> Dict[str, Any]:
    """Shard topology: the layout's declaration plus each pinned
    endpoint's probed health/ejection/breaker state, in shard order."""
    by_url = {ep["url"]: ep for ep in snap.get("endpoints", [])}
    stats = snap.get("endpoint_stats", {})
    shards = []
    for i, url in enumerate(layout.endpoints):
        ep = by_url.get(url, {})
        st = stats.get(url, {})
        shards.append({
            "shard": i,
            "url": url,
            "live": bool(ep.get("live")),
            "ready": bool(ep.get("ready")),
            "ejected": bool(st.get("ejected")),
            "breaker_state": st.get("breaker_state"),
            "outstanding": st.get("outstanding"),
        })
    return {"layout": layout.describe(), "shards": shards}


def _pipeline_section(pipeline, urls, protocol, client_factory,
                      timeout_s: float, runs: int = 4) -> Dict[str, Any]:
    """Probe the declared model DAG: run it a few times through a
    flight-armed PipelineClient over the fleet and report the waterfall
    — per-stage latencies, each run's dominant flight-attribution key
    (``pipeline:<stage>``), and the slab plan's high-water versus the
    arena residency the probe actually observed."""
    from .flight import FlightRecorder
    from .pipeline import PipelineClient

    feeds = {}
    for name, (dtype, shape) in pipeline.inputs.items():
        concrete = [1 if int(d) < 0 else int(d) for d in shape]
        np_dtype = triton_to_np_dtype(dtype)
        if np_dtype is None or np_dtype == np.object_:
            feeds[name] = np.full(concrete, b"0", dtype=np.object_)
        else:
            feeds[name] = np.ones(concrete, dtype=np_dtype)
    recorder = FlightRecorder(baseline_ratio=1.0)
    tel = Telemetry(sample="always", flight=recorder)
    section: Dict[str, Any] = {
        "pipeline": pipeline.name,
        "stages": list(pipeline.order),
        "runs": 0,
        "errors": [],
    }
    client = None
    try:
        client = PipelineClient(
            list(urls), pipeline, protocol=protocol, telemetry=tel,
            health_interval_s=None, client_factory=client_factory)
        try:
            # one unmeasured warmup run: the first execution bills every
            # stage's jit compile, which would crown a fake hot stage
            client.run(feeds, client_timeout=timeout_s)
        except InferenceServerException:
            pass  # a genuinely broken DAG will show up measured
        warm_seqs = {t.seq for t in recorder.retained()}
        samples: Dict[str, List[float]] = {}
        for _ in range(max(1, runs)):
            try:
                res = client.run(feeds, client_timeout=timeout_s)
                section["runs"] += 1
                for sname, lat_s in res.stage_latency_s.items():
                    samples.setdefault(sname, []).append(lat_s * 1e3)
            except InferenceServerException as e:
                section["errors"].append(str(e))
        section["stage_ms"] = {
            sname: {
                "count": len(vals),
                "avg_ms": round(sum(vals) / len(vals), 3),
                "p50_ms": round(sorted_percentile(sorted(vals), 0.50), 3),
                "max_ms": round(max(vals), 3),
            }
            for sname, vals in samples.items()}
        stats = client.stats()
        section["plan_high_water_bytes"] = stats.get(
            "plan_high_water_bytes")
        section["observed_high_water_bytes"] = stats.get(
            "observed_high_water_bytes")
        # per-run dominant attribution over the probe's own recorder:
        # every timeline is retained (baseline_ratio=1.0), so this is
        # the full measured population, not an anomaly sample
        dominant: Dict[str, int] = {}
        for timeline in recorder.retained():
            if timeline.seq in warm_seqs:
                continue
            att = timeline.attribution()
            key = att.get("dominant")
            if key:
                dominant[key] = dominant.get(key, 0) + 1
        section["dominant"] = dominant
        stage_rows = section["stage_ms"]
        total_avg = sum(row.get("avg_ms", 0.0)
                        for row in stage_rows.values())
        if stage_rows and total_avg > 0:
            hot = max(stage_rows, key=lambda k: stage_rows[k]["avg_ms"])
            section["hot_stage"] = hot
            section["hot_share"] = round(
                stage_rows[hot]["avg_ms"] / total_avg, 4)
    except InferenceServerException as e:
        section["error"] = str(e)
    finally:
        if client is not None:
            client.close()
    return section


def _registry_section(snapshot: Dict[str, Any], prefix: str) -> Dict[str, Any]:
    return {name: family for name, family in snapshot.items()
            if name.startswith(prefix) and family.get("series")}


def _anomalies(snap: Dict[str, Any], churn_threshold_ops_s: float,
               skew_warn_ms: float) -> List[Dict[str, Any]]:
    flags: List[Dict[str, Any]] = []
    for ep in snap["endpoints"]:
        url = ep["url"]
        if not ep.get("live") or not ep.get("ready"):
            flags.append({"flag": "endpoint_unhealthy", "url": url,
                          "detail": ep.get("health_error", "not ready")})
        if ep.get("probe_errors"):
            flags.append({"flag": "probe_errors", "url": url,
                          "detail": ep.get("probe_error", "")})
        skew = ep.get("clock_skew_ms")
        if skew is not None:
            slack = ep.get("clock_skew_uncertainty_ms", 0.0)
            if abs(skew) > skew_warn_ms + slack:
                flags.append({"flag": "clock_skew", "url": url,
                              "detail": f"{skew:+.1f} ms (±{slack:.1f})"})
    for url, stats in snap.get("endpoint_stats", {}).items():
        state = stats.get("breaker_state")
        if state and state != "closed":
            flags.append({"flag": "breaker_" + state, "url": url,
                          "detail": f"breaker {state}"})
        if stats.get("ejected"):
            flags.append({"flag": "endpoint_ejected", "url": url,
                          "detail": f"for {stats.get('ejected_for_s', 0)}s"})
        # byzantine replica: this endpoint is RESPONDING — transport is
        # healthy, the breaker sees successes — but what it returns fails
        # contract validation. Health probes will never catch it; only the
        # per-response integrity checks do. quarantined means it is
        # currently ejected FOR wrongness (not latency/errors), which is
        # the strongest possible signal that the replica itself is
        # corrupt: restart or reimage it, don't wait for readmission.
        if stats.get("quarantined"):
            flags.append({
                "flag": "byzantine_replica", "url": url,
                "detail": (f"quarantined after "
                           f"{stats.get('invalid_total', 0)} invalid "
                           f"responses (quarantine #"
                           f"{stats.get('quarantine_count', 0)}) — "
                           "replica answers probes but returns corrupt "
                           "payloads; restart or reimage it")})
        elif stats.get("invalid_total"):
            flags.append({
                "flag": "byzantine_replica", "url": url,
                "detail": (f"{stats['invalid_total']} responses failed "
                           "integrity validation (below the quarantine "
                           "threshold so far) — watch this replica")})
    # a sharded deployment has ZERO failover headroom: every logical
    # request needs EVERY pinned endpoint, so one degraded replica is a
    # whole-deployment outage, not an N-1 brownout — say so explicitly
    for row in (snap.get("shard") or {}).get("shards", []):
        problems = []
        if not row.get("ready"):
            problems.append("not ready")
        if row.get("ejected"):
            problems.append("ejected")
        breaker = row.get("breaker_state")
        if breaker and breaker != "closed":
            problems.append(f"breaker {breaker}")
        if problems:
            flags.append({
                "flag": "shard_degraded", "url": row["url"],
                "detail": (f"shard {row['shard']} pinned endpoint is "
                           f"{', '.join(problems)}; a sharded deployment "
                           "has zero failover headroom — every logical "
                           "request fails (typed ShardFailed) until this "
                           "replica recovers")})
    # disaggregated prefill/decode: a serving role with members but ZERO
    # routable ones means every role-aware session is degrading to the
    # monolithic fallback path — correct but silent capacity loss; the
    # pool's RoleFallback counter is the traffic-is-actually-flowing proof
    for role, row in (snap.get("roles") or {}).items():
        if row.get("endpoints", 0) > 0 and not row.get("available"):
            fallbacks = row.get("fallbacks", 0)
            detail = (f"role {role!r}: 0/{row['endpoints']} endpoints "
                      f"routable — role-aware traffic is falling back to "
                      f"monolithic serving")
            if fallbacks:
                detail += f" ({fallbacks} RoleFallback events counted)"
            flags.append({"flag": "role_degraded", "url": None,
                          "role": role, "detail": detail})
    # client-orchestrated DAG: one stage soaking up most of the graph's
    # wall time is the pipeline's capacity ceiling — replicate THAT
    # model, not the whole chain. Only meaningful with >= 2 stages (a
    # one-stage pipeline trivially dominates itself) and flagged off the
    # probe's own measured waterfall, not a heuristic.
    pipe = snap.get("pipeline") or {}
    hot = pipe.get("hot_stage")
    if (hot is not None and len(pipe.get("stages", [])) >= 2
            and pipe.get("hot_share", 0.0) >= 0.5):
        row = (pipe.get("stage_ms") or {}).get(hot, {})
        flags.append({
            "flag": "pipeline_stage_hot", "url": None, "stage": hot,
            "detail": (f"stage {hot!r} holds "
                       f"{pipe['hot_share']:.0%} of the DAG's stage "
                       f"time (avg {row.get('avg_ms', 0):.2f} ms over "
                       f"{pipe.get('runs', 0)} probe runs) — scale "
                       f"that model's replicas before the rest of the "
                       f"chain")})
    if pipe.get("errors"):
        flags.append({
            "flag": "pipeline_probe_errors", "url": None,
            "detail": (f"{len(pipe['errors'])} of "
                       f"{pipe['runs'] + len(pipe['errors'])} probe DAG "
                       f"runs failed: {pipe['errors'][0]}")})
    for slo in snap.get("slos", []):
        if slo["breached"]:
            flags.append({
                "flag": "slo_breached", "url": None,
                "detail": f"{slo['name']}: burn {slo['burn_rate']:.2f}x"})
    # admission collapse: the adaptive limit is pinned at its floor WHILE
    # an SLO is burning — the limiter has given all it can and latency is
    # still over target, i.e. the fleet is undersized (or a replica is
    # sick), not merely bursty. A floor-pinned limit on a quiet, in-SLO
    # fleet is just the idle state and is never flagged.
    slo_burning = any(s.get("breached") for s in snap.get("slos", []))
    for row in snap.get("admission", []) or []:
        if row.get("collapsed") and slo_burning:
            flags.append({
                "flag": "admission_collapse", "url": None,
                "detail": (f"scope {row.get('scope', 'pool')}: limit "
                           f"{row.get('limit')} pinned at floor "
                           f"{row.get('limiter', {}).get('min_limit')} "
                           f"with an SLO burning "
                           f"(shed_total={row.get('shed_total')})")})
    # multi-cell federation: a SERVING cell with nothing routable (or a
    # cell breaker open) is a whole-site outage in progress — every
    # request that preferred it is spilling or failing; spillover-active
    # means the shed-rate hysteresis is currently steering new traffic
    # past a cell (capacity is degraded even though users see no errors);
    # canary_burning means the canary's SLO burn tripped (or is tripping)
    # — the rollout is bad and the auto-rollback is the only thing
    # between it and the users
    for fedrow in snap.get("cells", []) or []:
        for name, cell in (fedrow.get("cells") or {}).items():
            pool = cell.get("pool") or {}
            breaker = cell.get("breaker_state")
            if cell.get("role") == "serve" and (
                    pool.get("available") is False or breaker == "open"):
                problems = []
                if pool.get("available") is False:
                    problems.append(
                        f"{pool.get('healthy', 0)}/"
                        f"{pool.get('endpoints', '?')} endpoints routable")
                if breaker and breaker != "closed":
                    problems.append(f"cell breaker {breaker}")
                flags.append({
                    "flag": "cell_down", "url": name,
                    "detail": ", ".join(problems) or "cell unavailable"})
            if cell.get("spill_active"):
                flags.append({
                    "flag": "spillover_active", "url": name,
                    "detail": (f"shed rate {cell.get('shed_rate')} over "
                               f"the hysteresis window; spill_out="
                               f"{sum((cell.get('spill_out') or {}).values())}")})
        canary = fedrow.get("canary")
        if canary and (canary.get("breached") or canary.get("rolled_back")):
            state = ("rolled back" if canary.get("rolled_back")
                     else "burning")
            flags.append({
                "flag": "canary_burning", "url": canary.get("cell"),
                "detail": (f"canary {state}: burn "
                           f"{canary.get('burn_rate')}x over "
                           f"{canary.get('ok', 0) + canary.get('bad', 0)} "
                           f"events (weight now "
                           f"{canary.get('weight')})")})
    # cache thrash: the response cache is churning entries out (capacity
    # evictions rival insertions) while barely serving hits — the cache
    # is sized below the workload's working set, so it burns staging work
    # for nothing. A small or cold cache with few lookups never flags.
    for row in snap.get("cache", []) or []:
        if "error" in row:
            continue
        lookups = (row.get("hits", 0) + row.get("stale_hits", 0)
                   + row.get("misses", 0))
        cap_evictions = (row.get("evictions") or {}).get("capacity", 0)
        insertions = row.get("insertions", 0)
        hit_rate = row.get("hit_rate") or 0.0
        if (lookups >= 50 and insertions > 0
                and cap_evictions >= 0.5 * insertions and hit_rate < 0.2):
            flags.append({
                "flag": "cache_thrash", "url": None,
                "detail": (f"{cap_evictions} capacity evictions over "
                           f"{insertions} insertions with hit rate "
                           f"{hit_rate:.0%} — the working set exceeds "
                           f"max_bytes={row.get('max_bytes')}")})
    # noisy neighbor: a tenant's over-quota sheds dwarf what it was
    # admitted — it is offering far beyond its declared rate, and only
    # the tenancy layer (token buckets + weighted-fair queues) stands
    # between its excess and the compliant tenants' capacity. Named per
    # tenant: the verdict comes from the policy's own counters, so it
    # holds even when the neighbors' latencies look healthy (isolation
    # working is not a reason to hide who is being isolated).
    for row in snap.get("tenancy", []) or []:
        if "error" in row:
            continue
        for verdict in row.get("noisy_neighbors", []) or []:
            flags.append({
                "flag": "noisy_neighbor", "url": None,
                "tenant": verdict.get("tenant"),
                "detail": (f"tenant {verdict.get('tenant')!r}: "
                           f"{verdict.get('over_quota_sheds')} over-quota "
                           f"sheds vs {verdict.get('admitted_total')} "
                           f"admitted (offered/admitted ~"
                           f"{verdict.get('offered_over_admitted')}x) — "
                           f"quotas are shedding its excess; compliant "
                           f"tenants keep their weighted share")})
    # affinity skew: one endpoint owns far more than its fair share of
    # the affinity key universe — hot keys are concentrating (a zipfian
    # workload's hottest keys hashed together, or the fleet shrank and
    # re-homing piled keys onto one survivor)
    aff = {url: stats["affinity"]
           for url, stats in snap.get("endpoint_stats", {}).items()
           if stats.get("affinity")}
    if len(aff) >= 2:
        total_keys = sum(a.get("keys", 0) for a in aff.values())
        if total_keys >= 16:
            url, top = max(aff.items(), key=lambda kv: kv[1].get("keys", 0))
            share = top.get("keys", 0) / total_keys
            # twice the fair share, clamped into (0.5, 0.9]: the 0.9 cap
            # keeps the flag reachable on a 2-endpoint pool (where 2x
            # fair share would be an unattainable 100%)
            if share > min(0.9, max(0.5, 2.0 / len(aff))):
                flags.append({
                    "flag": "affinity_skew", "url": url,
                    "detail": (f"owns {share:.0%} of {total_keys} tracked "
                               f"affinity keys across {len(aff)} endpoints "
                               f"(fair share {1.0 / len(aff):.0%})")})
    # tail divergence: the flight recorder's retained slow tail shares one
    # dominant attribution key (a layer, or a layer:endpoint pair) that
    # the baseline traffic does not — the one-bad-replica / one-hot-lock
    # signature, named per-request instead of inferred from aggregates
    divergence = (snap.get("flight") or {}).get("tail_divergence")
    if divergence:
        url = None
        dominant = divergence["dominant"]
        if ":" in dominant:
            url = dominant.split(":", 1)[1]
        flags.append({
            "flag": "tail_divergence", "url": url,
            "detail": (f"{divergence['tail_share']:.0%} of "
                       f"{divergence['tail_count']} retained slow-tail "
                       f"timelines are dominated by {dominant!r} "
                       f"(baseline share "
                       f"{divergence['baseline_share']:.0%})")})
    dataplane = snap.get("shm", {}).get("dataplane")
    if dataplane and churn_threshold_ops_s:
        # prefer the probe-window rate: the lifetime average of a
        # long-quiet process dilutes a burst happening right now
        churn = dataplane.get("churn_ops_per_s_window",
                              dataplane.get("churn_ops_per_s", 0.0))
        if churn > churn_threshold_ops_s:
            flags.append({
                "flag": "shm_churn_high", "url": None,
                "detail": f"{churn:.0f} ops/s > {churn_threshold_ops_s:.0f}"})
    leased = snap.get("shm", {}).get("arena_leased_bytes")
    if leased and leased["after_probe"] > leased["before_probe"]:
        # leased bytes did not return to the pre-probe baseline: some path
        # leased a slab during the probe and never released it
        flags.append({
            "flag": "shm_arena_leak", "url": None,
            "detail": (f"leased bytes {leased['before_probe']} -> "
                       f"{leased['after_probe']} over the probe")})
    # load/latency divergence: an endpoint much slower than the fleet
    # median whose server-side busy signal is NOT above median — the
    # extra milliseconds are outside the server (network, proxy, queueing
    # in front of it). Endpoints with NO server-side signal are never
    # flagged: without one the server cannot be ruled out as the cause.
    rows = [(ep["url"], ep["probe_latency_ms"]["avg"],
             _server_compute_us(snap, ep["url"]))
            for ep in snap["endpoints"] if "probe_latency_ms" in ep]
    if len(rows) >= 2:
        latencies = sorted(lat for _, lat, _ in rows)
        computes = sorted(c for _, _, c in rows if c is not None)
        # LOWER median: with the upper one a 2-endpoint fleet's slower
        # replica IS the median, so `lat > 2*median` could never fire
        median_lat = latencies[(len(latencies) - 1) // 2]
        median_compute = (computes[(len(computes) - 1) // 2]
                          if computes else None)
        for url, lat, compute_us in rows:
            if compute_us is None or median_compute is None:
                continue
            slow = lat > 2.0 * median_lat and lat - median_lat > 1.0
            if not slow:
                continue
            # does the server-side compute excess explain the latency
            # excess? A ratio test on raw compute is noise-prone (tiny
            # models compute in single-digit ms with same-magnitude
            # jitter); the divergence question is whether the EXTRA
            # milliseconds happened inside the server or outside it
            excess_lat_ms = lat - median_lat
            excess_compute_ms = max(compute_us - median_compute, 0.0) / 1e3
            if excess_compute_ms < 0.5 * excess_lat_ms:
                flags.append({
                    "flag": "load_latency_divergence", "url": url,
                    "detail": (f"latency {lat:.1f} ms vs fleet median "
                               f"{median_lat:.1f} ms, server compute "
                               f"explains {excess_compute_ms:.1f} ms of "
                               f"the {excess_lat_ms:.1f} ms excess")})
    # continuous-monitoring verdicts: the watchtower's ACTIVE alerts are
    # incidents in progress, distinct from the point-in-time probe flags
    # above. A changepoint trip is surfaced with the endpoint/layer the
    # flight divergence named (or the fleet-shift verdict) so the
    # snapshot says what moved, not just that something did.
    watch_sec = snap.get("watch") or {}
    for alert in watch_sec.get("active", []) or []:
        kind = alert.get("kind")
        evidence = alert.get("evidence") or {}
        if kind == "changepoint":
            flags.append({
                "flag": "changepoint", "url": None,
                "detail": (f"{alert.get('source')}: moved to "
                           f"{evidence.get('value')} from baseline "
                           f"{evidence.get('baseline_mean')} — "
                           f"{evidence.get('moved', 'fleet_shift')}")})
        else:
            flags.append({
                "flag": "alert_firing", "url": None,
                "detail": (f"{kind}:{alert.get('source')} "
                           f"severity={alert.get('severity')} since "
                           f"{alert.get('fired_unix')}")})
    return flags


def _server_compute_us(snap: Dict[str, Any], url: str) -> Optional[float]:
    """The endpoint's server-side busy signal: the decomposition's
    per-request server compute measured over the probe window, falling
    back to the ORCA-reported average. The window-scoped number comes
    first — ORCA's ``avg_compute_infer_us`` is a lifetime average, so
    one-time history (jit compile, warmup) can read as "busy" long after
    the endpoint went idle and mask a divergence happening now."""
    rows = [r for r in snap.get("decomposition", []) if r["url"] == url]
    if rows:
        return max(r["server_compute_ms"] for r in rows) * 1e3
    load = snap.get("endpoint_stats", {}).get(url, {}).get("load")
    if load:
        us = load["metrics"].get("named_metrics.avg_compute_infer_us")
        if us is not None:
            return us
    return None


def collect_snapshot(
    urls: Sequence[str],
    protocol: str = "http",
    model: str = "simple",
    requests_per_endpoint: int = 8,
    orca_format: Optional[str] = "json",
    telemetry: Optional[Telemetry] = None,
    churn_threshold_ops_s: float = 10000.0,
    skew_warn_ms: float = 250.0,
    probe_timeout_s: float = 10.0,
    client_factory: Optional[Callable[[str], Any]] = None,
    shard_layout=None,
    cells=None,
    roles=None,
    pipeline=None,
    pipeline_runs: int = 4,
    integrity: bool = False,
    watch: Optional[float] = None,
) -> Dict[str, Any]:
    """Probe the fleet and return the full snapshot dict (JSON-ready).

    ``orca_format`` configures the Telemetry the doctor builds for the
    probe; when a caller-supplied ``telemetry`` is passed it is used as
    is — its own ``orca_format`` (possibly None) wins, since mutating
    the caller's live telemetry mid-scrape would be worse than
    honoring its configuration.

    ``shard_layout``: a ``client_tpu.shard.ShardLayout`` (or its spec
    string, resolved over ``urls`` in order) describing a sharded
    deployment — adds a ``shard`` topology section and flags
    ``shard_degraded`` when any pinned endpoint is unhealthy, ejected or
    breaker-open.

    ``cells``: a ``{name: [urls]}`` dict (or its spec string,
    ``"a=u1+u2;b=u3"``) describing a multi-cell federation
    (``client_tpu.federation``): the doctor stands up a probe
    ``FederatedClient`` over the cells, direct-probes every cell's
    endpoints, and the snapshot gains a ``cells`` section (per-cell
    health, breaker state, spill/shadow/canary counters, SLO burn) plus
    the ``cell_down``/``spillover_active``/``canary_burning`` anomaly
    flags. With an empty ``urls``, the per-endpoint probe section covers
    the cells' urls. A caller-supplied ``telemetry`` that already has an
    application federation attached surfaces it in the same section —
    its LIVE spill counters, not the probe's.

    ``roles``: a ``{role: [urls]}`` dict (or its spec string,
    ``"prefill=u1+u2;decode=u3"``) labeling endpoints with serving
    roles (``client_tpu.disagg``): the probe pool is built with
    role-labeled ``EndpointSpec``s, the snapshot gains a ``roles``
    section (per-role endpoint/healthy counts, availability, counted
    RoleFallback events), and ``role_degraded`` is flagged for any role
    with members but zero routable ones — the state in which every
    role-aware session silently degrades to monolithic serving. With an
    empty ``urls``, the probe covers the roles' urls.

    ``pipeline``: a ``client_tpu.pipeline.Pipeline`` (or its spec
    string: ``"chain"`` or an inline graph spec) declaring a client-
    orchestrated model DAG: the doctor runs it ``pipeline_runs`` times
    through a flight-armed probe ``PipelineClient`` over the fleet and
    the snapshot gains a ``pipeline`` section (per-stage latency
    waterfall, each run's dominant flight attribution, slab-plan vs
    observed arena high-water) plus the ``pipeline_stage_hot`` anomaly
    when one stage dominates the DAG's wall time."""
    if isinstance(cells, str):
        from .federation import parse_cells_spec

        cells = parse_cells_spec(cells)
    if isinstance(roles, str):
        # same "name=u1+u2;name2=u3" grammar as --cells
        from .federation import parse_cells_spec

        roles = parse_cells_spec(roles)
    urls = list(urls)
    if cells and not urls:
        urls = [u for cell_urls in cells.values() for u in cell_urls]
    if roles and not urls:
        urls = [u for role_urls in roles.values() for u in role_urls]
    role_by_url: Dict[str, str] = {}
    for role, role_urls in (roles or {}).items():
        for u in role_urls:
            role_by_url[u] = role
    if isinstance(shard_layout, str):
        from .shard import ShardLayout

        shard_layout = ShardLayout.parse(shard_layout, list(urls))
    if isinstance(pipeline, str):
        from .pipeline import resolve_pipeline

        pipeline = resolve_pipeline(pipeline)
    tel = telemetry
    if tel is None:
        tel = Telemetry(sample="always", orca_format=orca_format,
                        trace_capacity=max(
                            1024, requests_per_endpoint * len(urls) * 2))
    recorder = observe.dataplane()
    scoped_recorder = recorder is None
    if scoped_recorder:
        # CLI runs (and hosts that never enabled accounting) still get a
        # populated data-plane section and a live churn window — counting
        # THIS process's shm ops (zero unless this process touches shm)
        # rather than silently reporting None. With a caller-supplied
        # Telemetry the recorder gets its own registry: probe-scoped shm
        # instruments must not render frozen on the caller's long-lived
        # scrape after the recorder is uninstalled below
        recorder = observe.enable_dataplane(
            tel.registry if telemetry is None else None)
    mod = _input_module(protocol)
    if client_factory is None:
        client_factory = _bounded_client_factory(protocol, probe_timeout_s)
    fed = None
    pool_urls = [EndpointSpec(u, role=role_by_url.get(u)) for u in urls]
    pool = PoolClient(pool_urls, protocol=protocol, telemetry=tel,
                      health_interval_s=None,
                      client_factory=client_factory)
    try:
        if cells:
            from .federation import FederatedClient

            # a probe federation: attaches itself to ``tel`` so the
            # cells section below reads it like any application
            # federation; every transport call is bounded by the probe
            # factory/timeouts
            fed = FederatedClient(
                cells, protocol=protocol, telemetry=tel,
                pool_kwargs={"health_interval_s": None,
                             "client_factory": client_factory})
        correlator = StatsCorrelator(tel, pool,
                                     call_timeout_s=probe_timeout_s)
        correlator.poll_once()  # baseline for the decomposition deltas
        dataplane_before = (recorder.snapshot()
                            if recorder is not None else None)
        arena_leased_before = _arena_leased_bytes()
        probe_t0 = time.monotonic()
        endpoints = []
        for ep in pool.pool.endpoints:
            report = _probe_endpoint(
                ep, mod, model, requests_per_endpoint, probe_timeout_s)
            # feed the manual probe verdict into the engine so
            # endpoint_stats reflects what the doctor just observed
            pool.pool.set_health(ep, report.get("ready", False))
            endpoints.append(report)
        if fed is not None:
            # direct-probe every cell's endpoints so the cells section
            # reflects what is routable RIGHT NOW, not construction-time
            # optimism (wait_healthy probes each endpoint once and feeds
            # pool.set_health — bounded by probe_timeout_s per call)
            fed.wait_healthy(timeout_s=probe_timeout_s)
        correlator.poll_once()
        tel.flush()
        registry_snapshot = tel.registry.snapshot()
        snap: Dict[str, Any] = {
            "generated_unix": int(time.time()),
            "urls": list(urls),
            "protocol": protocol,
            "model": model,
            "endpoints": endpoints,
            "endpoint_stats": pool.endpoint_stats(),
            # per-endpoint probe averages: the network+client remainder
            # is attributed to the endpoint that paid it, not a fleet mean
            "decomposition": correlator.decomposition(client_ms_by_url={
                ep["url"]: ep["probe_latency_ms"]["avg"]
                for ep in endpoints if "probe_latency_ms" in ep}),
            "slos": _slo_status(tel),
            "admission": _admission_status(tel),
            "cells": _federation_status(tel),
            "stream_windows": _registry_section(
                registry_snapshot, "client_tpu_stream_window"),
            "batch": _registry_section(
                registry_snapshot, "client_tpu_batch"),
            "cache": _cache_status(),
            "tenancy": _tenancy_status(),
            "flight": _flight_status(tel),
            "shm": _local_shm(recorder),
        }
        server_shm: Dict[str, Any] = {}
        for ep in pool.pool.endpoints:
            server_shm[ep.url] = _server_shm_status(ep.client,
                                                    probe_timeout_s)
        if shard_layout is not None:
            snap["shard"] = _shard_section(shard_layout, snap)
        if pipeline is not None:
            snap["pipeline"] = _pipeline_section(
                pipeline, urls, protocol, client_factory,
                probe_timeout_s, pipeline_runs)
        role_summary = pool.health_summary().get("roles")
        if role_summary:
            snap["roles"] = role_summary
        snap["shm"]["server_regions"] = server_shm
        dp = snap["shm"]["dataplane"]
        if dp is not None and dataplane_before is not None:
            # churn over the probe window, not the recorder's lifetime: a
            # long-quiet process must still flag a burst happening NOW
            window_s = max(time.monotonic() - probe_t0, 1e-9)
            dp["churn_ops_per_s_window"] = round(
                max(_total_dataplane_ops(dp)
                    - _total_dataplane_ops(dataplane_before), 0.0)
                / window_s, 3)
        # arena leak check: leased bytes must return to the pre-probe
        # baseline once the probe's requests have settled — growth means
        # some path leased without releasing. Application traffic on other
        # threads holds transient leases mid-infer, so a raised reading is
        # re-sampled after short settles and only the settled value is
        # compared (false flags would make the anomaly untrustworthy).
        arena_leased_after = _arena_leased_bytes()
        for _ in range(3):
            if arena_leased_after <= arena_leased_before:
                break
            time.sleep(0.2)
            arena_leased_after = _arena_leased_bytes()
        snap["shm"]["arena_leased_bytes"] = {
            "before_probe": arena_leased_before,
            "after_probe": arena_leased_after,
        }
        # response-integrity section: the process-wide validation
        # counters (every contract-checked response in THIS process, not
        # just the probe's own requests) next to the per-endpoint
        # quarantine view the anomaly pass reads. The overhead
        # percentiles answer "what does always-on validation cost" with
        # measured ns, not an estimate.
        if integrity:
            from . import integrity as _integrity_mod
            snap["integrity"] = _integrity_mod.global_stats().snapshot()
        # continuous-monitoring section: --watch SECONDS runs a live
        # fast-tick watchtower over the probe telemetry (burn + watermark
        # + changepoint rules all armed); without it, a process-global
        # watchtower (enable_watchtower) is snapshotted if installed
        watch_section = _watch_status(tel, watch)
        if watch_section is not None:
            snap["watch"] = watch_section
        snap["anomalies"] = _anomalies(
            snap, churn_threshold_ops_s, skew_warn_ms)
        return snap
    finally:
        pool.close()
        if fed is not None:
            fed.close()
        if scoped_recorder:
            observe.install_dataplane(None)


def _watch_status(tel: Telemetry, watch_s: Optional[float],
                  ) -> Optional[Dict[str, Any]]:
    """The snapshot's ``watch`` section. ``watch_s`` > 0 arms a scoped
    fast-tick watchtower on the probe telemetry for that long (live
    mode); otherwise the process-global watchtower is snapshotted if one
    is installed, and the section is omitted entirely if not."""
    from . import watch as watch_mod

    if watch_s is not None and watch_s > 0:
        tower = watch_mod.Watchtower(
            tel, interval_s=max(float(watch_s) / 20.0, 0.05))
        try:
            deadline = time.monotonic() + float(watch_s)
            while True:
                tower.tick()
                if time.monotonic() >= deadline:
                    break
                time.sleep(tower.interval_s)
            return tower.snapshot()
        finally:
            tower.stop()
    tower = watch_mod.watchtower()
    return tower.snapshot() if tower is not None else None


# every section the bundle PROMOTES to its top level when the snapshot
# carries it — the completeness contract tests pin the bundle to: a new
# snapshot section must be added here (and to the docs) or the
# completeness test fails, so the bundle can't silently go stale again
POSTMORTEM_SECTIONS = ("tenancy", "roles", "integrity", "pipeline",
                       "shard", "cells", "watch")


def postmortem_bundle(snapshot: Dict[str, Any],
                      telemetry: Optional[Telemetry] = None,
                      ) -> Dict[str, Any]:
    """Package one fleet snapshot into a self-contained, JSON-pure
    postmortem artifact: the snapshot (endpoint/admission/cache/arena
    state + anomaly flags), the flight recorder's FULL retained
    timelines (the snapshot carries only summaries), the telemetry's
    metrics snapshot and the SLO report. One file answers "what was the
    fleet doing, and why were the slow requests slow" without a live
    process to interrogate — write it the moment the incident happens,
    not after the evidence has aged out of the rings.

    ``sections`` is the completeness manifest: every key the snapshot
    carries, verbatim — a reader (or the completeness test) checks it
    against the snapshot instead of trusting the bundle's age. The
    :data:`POSTMORTEM_SECTIONS` present in the snapshot (tenancy, roles,
    integrity, pipeline, shard, cells, watch) are additionally promoted
    to the bundle's top level for direct access, and a live
    process-global watchtower contributes its alert state as ``watch``
    even when the snapshot predates it."""
    bundle: Dict[str, Any] = {
        "kind": "client_tpu_postmortem",
        "version": 2,
        "generated_unix": int(time.time()),
        "snapshot": snapshot,
        "sections": sorted(snapshot.keys()),
    }
    for section in POSTMORTEM_SECTIONS:
        if section in snapshot:
            bundle[section] = snapshot[section]
    if "watch" not in bundle:
        from . import watch as watch_mod

        tower = watch_mod.watchtower()
        if tower is not None:
            bundle["watch"] = tower.snapshot()
    recorder = getattr(telemetry, "flight", None) \
        if telemetry is not None else None
    if recorder is not None:
        bundle["flight"] = {
            "stats": recorder.stats(),
            "tail_divergence": recorder.tail_divergence(),
            "timelines": [t.as_dict() for t in recorder.retained()],
        }
    if telemetry is not None:
        bundle["metrics"] = telemetry.registry.snapshot()
        bundle["slo_report"] = telemetry.slo_report()
    return bundle


def render_summary(snap: Dict[str, Any]) -> str:
    """The human-readable side of the snapshot."""
    lines: List[str] = []
    lines.append(f"client_tpu doctor — {len(snap['urls'])} endpoint(s), "
                 f"protocol {snap['protocol']}, model {snap['model']}")
    lines.append("")
    lines.append("endpoints:")
    for ep in snap["endpoints"]:
        state = ("ready" if ep.get("ready")
                 else ("live" if ep.get("live") else "DOWN"))
        row = f"  {ep['url']:<24} {state:<6}"
        lat = ep.get("probe_latency_ms")
        if lat:
            row += f" probe p50 {lat['p50']:.2f} ms (avg {lat['avg']:.2f})"
        skew = ep.get("clock_skew_ms")
        if skew is not None:
            row += f"  skew {skew:+.1f} ms"
        stats = snap.get("endpoint_stats", {}).get(ep["url"], {})
        breaker = stats.get("breaker_state")
        if breaker and breaker != "closed":
            row += f"  breaker={breaker}"
        load = stats.get("load")
        if load:
            busy = load["metrics"].get("named_metrics.avg_compute_infer_us")
            if busy is not None:
                row += f"  orca compute {busy / 1e3:.2f} ms"
        lines.append(row)
    rows = snap.get("decomposition") or []
    if rows:
        lines.append("")
        lines.append("latency decomposition (per request over the probe "
                     "window):")
        for row in rows:
            parts = [f"  {row['url']:<24} {row['model']:<18}"
                     f" n={row['requests']:<4}"
                     f" queue {row['server_queue_ms']:.2f} ms"
                     f" compute {row['server_compute_ms']:.2f} ms"]
            if "network_client_overhead_ms" in row:
                parts.append(
                    f" network+client {row['network_client_overhead_ms']:.2f}"
                    f" ms (client total {row['client_request_ms']:.2f} ms)")
            lines.append("".join(parts))
    shard = snap.get("shard")
    if shard:
        lines.append("")
        layout = shard.get("layout", {})
        lines.append(
            f"shard topology ({layout.get('shards')} shards; inputs "
            f"{layout.get('inputs')} -> outputs {layout.get('outputs')}):")
        for row in shard.get("shards", []):
            state = "ready" if row.get("ready") else "DEGRADED"
            extra = []
            if row.get("ejected"):
                extra.append("ejected")
            breaker = row.get("breaker_state")
            if breaker and breaker != "closed":
                extra.append(f"breaker={breaker}")
            lines.append(
                f"  shard {row['shard']}: {row['url']:<24} {state}"
                f"{('  ' + ' '.join(extra)) if extra else ''}")
    roles = snap.get("roles")
    if roles:
        lines.append("")
        lines.append("roles (disaggregated prefill/decode):")
        for role, row in roles.items():
            state = "available" if row.get("available") else "DEGRADED"
            extra = ""
            if row.get("fallbacks"):
                extra = f"  fallbacks={row['fallbacks']}"
            lines.append(
                f"  {role:<10} {state:<10} healthy "
                f"{row.get('healthy', '?')}/{row.get('endpoints', '?')}"
                f"{extra}")
    pipe = snap.get("pipeline")
    if pipe:
        lines.append("")
        if "error" in pipe:
            lines.append(f"pipeline ({pipe.get('pipeline')}): "
                         f"{pipe['error']}")
        else:
            lines.append(
                f"pipeline ({pipe['pipeline']}; "
                f"{len(pipe.get('stages', []))} stages, "
                f"{pipe.get('runs', 0)} probe runs):")
            stage_ms = pipe.get("stage_ms") or {}
            dominant = pipe.get("dominant") or {}
            for sname in pipe.get("stages", []):
                row = stage_ms.get(sname) or {}
                hot = " HOT" if sname == pipe.get("hot_stage") and (
                    pipe.get("hot_share", 0.0) >= 0.5) else ""
                dom = dominant.get(f"pipeline:{sname}", 0)
                lines.append(
                    f"  {sname:<16} avg {row.get('avg_ms', 0):.2f} ms "
                    f"p50 {row.get('p50_ms', 0):.2f} ms max "
                    f"{row.get('max_ms', 0):.2f} ms  dominant in "
                    f"{dom}/{pipe.get('runs', 0)} runs{hot}")
            lines.append(
                f"  arena high-water: plan "
                f"{pipe.get('plan_high_water_bytes')}B observed "
                f"{pipe.get('observed_high_water_bytes')}B")
    for fedrow in snap.get("cells") or []:
        if "error" in fedrow:
            lines.append("")
            lines.append(f"cells ({fedrow.get('scope')}): {fedrow['error']}")
            continue
        lines.append("")
        lines.append(
            f"cells ({fedrow.get('scope', 'federation')}; home "
            f"{fedrow.get('home')}, order "
            f"{'->'.join(fedrow.get('order', []))}):")
        for name, cell in (fedrow.get("cells") or {}).items():
            pool_row = cell.get("pool") or {}
            state = ("UP" if pool_row.get("available")
                     else ("DOWN" if pool_row else "?"))
            extra = []
            breaker = cell.get("breaker_state")
            if breaker and breaker != "closed":
                extra.append(f"breaker={breaker}")
            if cell.get("spill_active"):
                extra.append(f"SPILLING (shed {cell.get('shed_rate')})")
            spills = sum((cell.get("spill_out") or {}).values())
            lines.append(
                f"  {name:<10} {cell.get('role', 'serve'):<7} {state:<5}"
                f" healthy {pool_row.get('healthy', '?')}/"
                f"{pool_row.get('endpoints', '?')}"
                f"  served={cell.get('served', 0)}"
                f" spill_out={spills} spill_in={cell.get('spill_in', 0)}"
                f"{('  ' + ' '.join(extra)) if extra else ''}")
        shadow = fedrow.get("shadow")
        if shadow:
            lines.append(
                f"  shadow -> {shadow['cell']} ratio={shadow['ratio']:g} "
                f"sent={shadow['sent']} matched={shadow['matched']} "
                f"diverged={shadow['diverged']} errors={shadow['errors']} "
                f"skipped={shadow['skipped']}")
        canary = fedrow.get("canary")
        if canary:
            state = ("ROLLED BACK" if canary.get("rolled_back")
                     else ("BURNING" if canary.get("breached") else "ok"))
            lines.append(
                f"  canary -> {canary['cell']} weight="
                f"{canary.get('weight'):g} "
                f"(declared {canary.get('declared_weight'):g}) "
                f"routed={canary.get('routed', 0)} "
                f"ok={canary.get('ok', 0)} bad={canary.get('bad', 0)} "
                f"burn={canary.get('burn_rate')}x  {state}")
    admission = snap.get("admission") or []
    if admission:
        lines.append("")
        lines.append("admission:")
        for row in admission:
            if "error" in row:
                lines.append(f"  {row.get('scope', 'pool')}: {row['error']}")
                continue
            sheds = sum(
                n for lane in row.get("lanes", {}).values()
                for n in lane.get("shed", {}).values())
            lines.append(
                f"  {row.get('scope', 'pool'):<8} limit={row['limit']} "
                f"inflight={row['inflight']} "
                f"admitted={row['admitted_total']} shed={sheds}"
                f"{'  COLLAPSED' if row.get('collapsed') else ''}")
    slos = snap.get("slos") or []
    if slos:
        lines.append("")
        lines.append("slos:")
        for slo in slos:
            verdict = "BREACHED" if slo["breached"] else "ok"
            lines.append(
                f"  {slo['name']:<20} {slo['metric']} < "
                f"{slo['threshold_ms']:g} ms @ {slo['objective']:.0%}"
                f"  burn {slo['burn_rate']:.2f}x  {verdict}")
    cache_rows = snap.get("cache") or []
    if cache_rows:
        lines.append("")
        lines.append("response cache:")
        for row in cache_rows:
            if "error" in row:
                lines.append(f"  cache: {row['error']}")
                continue
            hit_rate = row.get("hit_rate")
            ev = row.get("evictions") or {}
            lines.append(
                f"  entries={row.get('entries')} "
                f"resident={row.get('bytes_resident')}B "
                f"hit_rate={'n/a' if hit_rate is None else f'{hit_rate:.0%}'} "
                f"evictions={sum(ev.values())} "
                f"(capacity={ev.get('capacity', 0)} ttl={ev.get('ttl', 0)})")
    tenancy_rows = snap.get("tenancy") or []
    if tenancy_rows:
        lines.append("")
        lines.append("tenancy:")
        for row in tenancy_rows:
            if "error" in row:
                lines.append(f"  tenancy: {row['error']}")
                continue
            for label, t in sorted((row.get("tenants") or {}).items()):
                window = t.get("window") or {}
                sheds = sum((t.get("shed") or {}).values())
                tokens = t.get("quota_tokens")
                burn = window.get("burn_rate")
                lines.append(
                    f"  {label:<16} admitted={t.get('admitted_total', 0)} "
                    f"shed={sheds} "
                    f"tokens={'n/a' if tokens is None else f'{tokens:.1f}'} "
                    f"burn={'n/a' if burn is None else f'{burn:.2f}x'}"
                    f"{'  BREACHED' if window.get('breached') else ''}")
    aff_stats = {url: s["affinity"]
                 for url, s in snap.get("endpoint_stats", {}).items()
                 if s.get("affinity")}
    if aff_stats:
        lines.append("")
        lines.append("affinity routing:")
        for url, a in aff_stats.items():
            lines.append(
                f"  {url:<24} routed={a.get('routed', 0)} "
                f"rehomed={a.get('rehomed', 0)} "
                f"spilled={a.get('spilled', 0)} keys={a.get('keys', 0)}")
    shm = snap.get("shm", {})
    dataplane = shm.get("dataplane")
    if dataplane:
        lines.append("")
        lines.append("data plane (this process):")
        for family, row in dataplane.get("families", {}).items():
            if not any(row.values()):
                continue
            lines.append(
                f"  {family:<7} regions={row['regions']:.0f} "
                f"resident={row['bytes_resident']:.0f}B "
                f"peak={row['bytes_peak']:.0f}B "
                f"created={row['created']:.0f} "
                f"destroyed={row['destroyed']:.0f}")
        lines.append(
            f"  churn {dataplane.get('churn_ops_per_s', 0):.1f} ops/s")
    for row in shm.get("arena") or []:
        stats = row.get("stats")
        if not stats:
            continue
        hit_rate = stats.get("hit_rate")
        cache = row.get("registration_cache") or {}
        lines.append(
            f"  arena  regions={stats['regions']} "
            f"leased={stats['leased_bytes']}B free={stats['free_bytes']}B "
            f"hit_rate={'n/a' if hit_rate is None else f'{hit_rate:.0%}'} "
            f"reg_cache={sum(len(v) for v in cache.values())} entries"
            f"/{len(cache)} endpoints")
    inventory = shm.get("local_inventory") or []
    if inventory:
        lines.append(f"  local regions: "
                     f"{', '.join(r['name'] for r in inventory)}")
    fl = snap.get("flight")
    if fl:
        stats = fl["stats"]
        lines.append("")
        lines.append(
            f"flight recorder: {stats['retained_total']} retained of "
            f"{stats['requests']} requests "
            f"(ring {stats['ring']}/{stats['capacity']}, "
            f"dropped {stats['dropped']})")
        for row in fl.get("last_anomalies", [])[:4]:
            lines.append(
                f"  {row['verdict']:<10} {row['model']:<16} "
                f"{row['duration_ms']:.1f} ms  dominant="
                f"{row['dominant']}  trace={row['trace_id']}")
    integ = snap.get("integrity")
    if integ:
        lines.append("")
        oh = integ.get("overhead_ns") or {}
        lines.append(
            f"integrity: {integ['results']} results validated, "
            f"{integ['checks']} checks, {integ['violations']} violations"
            + (f"  overhead p50={oh['p50'] / 1e3:.1f}us "
               f"p99={oh['p99'] / 1e3:.1f}us"
               if oh.get("samples") else ""))
        for kind, n in sorted((integ.get("violations_by_kind")
                               or {}).items()):
            lines.append(f"  violation kind {kind}: {n}")
        for url, n in sorted((integ.get("violations_by_url")
                              or {}).items()):
            lines.append(f"  violating url {url}: {n}")
    watch_sec = snap.get("watch")
    if watch_sec:
        lines.append("")
        tick = watch_sec.get("tick_ns") or {}
        lines.append(
            f"watch: {watch_sec.get('ticks', 0)} ticks, "
            f"{watch_sec.get('alerts_fired_total', 0)} alerts fired / "
            f"{watch_sec.get('alerts_resolved_total', 0)} resolved, "
            f"{watch_sec.get('changepoint_trips', 0)} changepoint trips"
            + (f"  (tick p50={tick['p50'] / 1e3:.1f}us "
               f"p99={tick['p99'] / 1e3:.1f}us)" if tick else ""))
        for alert in watch_sec.get("active", []) or []:
            ev = alert.get("evidence") or {}
            moved = ev.get("moved") or ev.get("divergence", {})
            lines.append(
                f"  FIRING {alert.get('kind')}:{alert.get('source')} "
                f"severity={alert.get('severity')}"
                + (f"  moved={moved}" if moved else ""))
        for row in (watch_sec.get("recent") or [])[-4:]:
            if row.get("state") == "resolved":
                lines.append(
                    f"  resolved {row.get('kind')}:{row.get('source')} "
                    f"after "
                    f"{(row.get('resolved_unix') or 0) - (row.get('fired_unix') or 0):.1f}s")
    anomalies = snap.get("anomalies") or []
    lines.append("")
    if anomalies:
        lines.append(f"ANOMALIES ({len(anomalies)}):")
        for flag in anomalies:
            where = f" [{flag['url']}]" if flag.get("url") else ""
            lines.append(f"  !! {flag['flag']}{where}: {flag['detail']}")
    else:
        lines.append("no anomalies detected")
    return "\n".join(lines)


def _render_blackbox(doc: Dict[str, Any]) -> str:
    """Human-readable rendering of a :func:`watch.blackbox_report`
    reconstruction — what the operator reads after the kill -9."""
    lines = [f"client_tpu blackbox reconstruction — {doc['path']}"]
    if not doc.get("ok"):
        lines.append(f"  UNREADABLE: {doc.get('note')}")
        return "\n".join(lines)
    scan = doc.get("scan") or {}
    lines.append(
        f"  {doc.get('records', 0)} records verified "
        f"({scan.get('rejected', 0)} rejected by checksum/format) from a "
        f"{scan.get('capacity_bytes', 0)}B ring")
    meta = doc.get("meta")
    if meta:
        lines.append(f"  writer: pid={meta.get('pid')} "
                     f"started_unix={meta.get('started_unix')} "
                     f"interval={meta.get('interval_s')}s")
    lines.append(
        f"  flight timelines recovered: {doc.get('timelines_recovered', 0)}"
        f" (showing last {len(doc.get('timelines') or [])})")
    for tl in (doc.get("timelines") or [])[-6:]:
        lines.append(
            f"    {tl.get('verdict', '?'):<10} {tl.get('model', ''):<16} "
            f"{tl.get('duration_ms', 0):.1f} ms  "
            f"dominant={(tl.get('attribution') or {}).get('dominant')}")
    metrics = doc.get("metrics")
    lines.append(
        f"  metrics snapshots recovered: "
        f"{doc.get('metrics_snapshots_recovered', 0)}"
        + (f" (last carries {len(metrics)} families)" if metrics else ""))
    alerts = doc.get("alerts") or []
    lines.append(f"  alerts recovered: {len(alerts)}")
    for alert in alerts[-6:]:
        lines.append(
            f"    {alert.get('state', '?'):<9} "
            f"{alert.get('kind')}:{alert.get('source')} "
            f"severity={alert.get('severity')} "
            f"fired_unix={alert.get('fired_unix')}")
    last = doc.get("last_alert")
    if last:
        lines.append(
            f"  last alert: {last.get('kind')}:{last.get('source')} "
            f"({last.get('state')})")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m client_tpu.doctor",
        description="One-command fleet snapshot for a client_tpu "
                    "deployment (health, breakers, ORCA load, latency "
                    "decomposition, shm inventory, anomalies).")
    parser.add_argument("urls", nargs="*", default=[],
                        help="replica host:port urls (optional when "
                             "--cells is given: the cells' urls are "
                             "probed)")
    parser.add_argument("--protocol", choices=("http", "grpc"),
                        default="http")
    parser.add_argument("--model", default="simple",
                        help="model to probe (inputs synthesized from its "
                             "metadata)")
    parser.add_argument("--requests", type=int, default=8,
                        help="probe infers per endpoint")
    parser.add_argument("--orca", choices=("json", "text"), default="json",
                        help="ORCA endpoint-load-metrics format to request")
    parser.add_argument("--churn-threshold", type=float, default=10000.0,
                        help="shm churn ops/s above which to flag")
    parser.add_argument("--skew-warn-ms", type=float, default=250.0)
    parser.add_argument("--shard-layout", default=None,
                        help="sharded-deployment layout spec over the "
                             "given urls in shard order, e.g. "
                             "'TOKENS=0->LOGITS=0,NEXT_TOKEN=0': adds the "
                             "shard topology section and the "
                             "shard_degraded anomaly (client_tpu.shard)")
    parser.add_argument("--cells", default=None, metavar="SPEC",
                        help="multi-cell federated snapshot: "
                             "'a=u1+u2;b=u3' stands up a probe "
                             "FederatedClient over the named cells and "
                             "adds the per-cell section (health, breaker, "
                             "spill/shadow/canary counters, SLO burn) "
                             "plus the cell_down/spillover_active/"
                             "canary_burning anomaly flags "
                             "(client_tpu.federation)")
    parser.add_argument("--roles", default=None, metavar="SPEC",
                        help="role-labeled snapshot for a disaggregated "
                             "prefill/decode fleet: "
                             "'prefill=u1+u2;decode=u3' labels the probe "
                             "pool's endpoints, adds the per-role section "
                             "(healthy counts, availability, RoleFallback "
                             "events) and flags role_degraded for any "
                             "role with zero routable members "
                             "(client_tpu.disagg)")
    parser.add_argument("--pipeline", default=None, metavar="SPEC",
                        help="client-orchestrated model-DAG probe: "
                             "'chain' (the zoo's tokenize->embed->rerank "
                             "chain) or an inline graph spec runs the "
                             "DAG through a flight-armed PipelineClient "
                             "over the fleet, adds the pipeline section "
                             "(per-stage waterfall, dominant flight "
                             "attribution, slab-plan vs observed arena "
                             "high-water) and flags pipeline_stage_hot "
                             "when one stage dominates "
                             "(client_tpu.pipeline)")
    parser.add_argument("--pipeline-runs", type=int, default=4,
                        help="probe DAG executions for --pipeline")
    parser.add_argument("--integrity", action="store_true",
                        help="add the response-integrity section: the "
                             "process-wide contract-validation counters "
                             "(results checked, violations by kind and "
                             "by url, measured per-response overhead "
                             "p50/p99) from client_tpu.integrity; "
                             "byzantine_replica anomalies are always "
                             "flagged off endpoint quarantine state, "
                             "with or without this flag")
    parser.add_argument("--timeout", type=float, default=10.0,
                        help="per-call timeout (s) bounding every snapshot "
                             "RPC: health probes, probe infers, stats "
                             "polls, metadata and shm-status calls")
    parser.add_argument("--json", dest="json_path", default=None,
                        help="also write the snapshot JSON artifact here")
    parser.add_argument("--postmortem", dest="postmortem_path",
                        default=None, metavar="PATH",
                        help="write a self-contained postmortem bundle "
                             "(snapshot + metrics + SLO report + the "
                             "flight recorder's full retained timelines; "
                             "arms a flight recorder on the probe "
                             "telemetry)")
    parser.add_argument("--watch", type=float, default=None,
                        metavar="SECONDS",
                        help="live continuous-monitoring mode: arm a "
                             "fast-tick Watchtower (burn-rate, watermark "
                             "and changepoint rules) over the probe "
                             "telemetry for SECONDS, and add the watch "
                             "section (active alerts, detector states, "
                             "tick overhead) plus the alert_firing/"
                             "changepoint anomalies (client_tpu.watch)")
    parser.add_argument("--blackbox", dest="blackbox_path", default=None,
                        metavar="PATH",
                        help="read a crash-safe black-box ring file "
                             "(client_tpu.watch.BlackBox) instead of "
                             "probing a fleet: reconstructs the retained "
                             "flight timelines, the last metrics "
                             "snapshot and the alert history from the "
                             "ring alone — works after a kill -9, needs "
                             "no live process; torn records are skipped, "
                             "never fatal")
    parser.add_argument("--fail-on-anomaly", action="store_true",
                        help="exit 1 when any anomaly is flagged")
    args = parser.parse_args(argv)
    if args.blackbox_path:
        from . import watch as watch_mod

        doc = watch_mod.blackbox_report(args.blackbox_path)
        print(_render_blackbox(doc))
        if args.json_path:
            with open(args.json_path, "w") as f:
                json.dump(doc, f, indent=2, default=str)
            print(f"\nblackbox report written to {args.json_path}")
        return 0 if doc["ok"] else 1
    if not args.urls and not args.cells and not args.roles:
        parser.error("give replica urls, --cells 'a=u1+u2;b=u3', "
                     "--roles 'prefill=u1;decode=u2', or --blackbox PATH")

    tel = None
    if args.postmortem_path:
        # a flight-armed probe telemetry: the probe requests themselves
        # are recorded, so even a cold process's bundle carries per-
        # request evidence about the fleet it just touched
        tel = Telemetry(sample="always", orca_format=args.orca,
                        flight=True)
    snap = collect_snapshot(
        args.urls, protocol=args.protocol, model=args.model,
        requests_per_endpoint=args.requests, orca_format=args.orca,
        telemetry=tel,
        churn_threshold_ops_s=args.churn_threshold,
        skew_warn_ms=args.skew_warn_ms, probe_timeout_s=args.timeout,
        shard_layout=args.shard_layout, cells=args.cells,
        roles=args.roles, pipeline=args.pipeline,
        pipeline_runs=args.pipeline_runs, integrity=args.integrity,
        watch=args.watch)
    print(render_summary(snap))
    if args.json_path:
        with open(args.json_path, "w") as f:
            json.dump(snap, f, indent=2, default=str)
        print(f"\nsnapshot written to {args.json_path}")
    if args.postmortem_path:
        bundle = postmortem_bundle(snap, tel)
        with open(args.postmortem_path, "w") as f:
            json.dump(bundle, f, indent=2, default=str)
        print(f"postmortem bundle written to {args.postmortem_path}")
    if args.fail_on_anomaly and snap.get("anomalies"):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
