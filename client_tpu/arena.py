"""Pooled shared-memory arena: the zero-copy data plane's allocator.

Before this module, every shm use-site created, registered and destroyed its
own region (mmap + registration RPC per use-site; five independent such
blocks in ``perf.py`` alone) — under sustained traffic that churn IS the
data-plane cost. The arena flips the steady-state cost model:

- **Size-class slabs carved from a few large regions.** A lease request is
  rounded up to a power-of-two class and served from a free slab; only a
  cold class mmaps a new region (carved into many slabs at once), so
  steady-state region create/destroy ops are zero.
- **Ref-counted leases.** :class:`ArenaLease` is the handle a slab is held
  by: ``retain()``/``release()`` are thread-safe AND asyncio-safe (one
  short-held lock, no blocking waits), a double release raises, and a
  zero-copy ``as_numpy`` view taken after the last release raises
  :class:`ArenaLeaseReleased` instead of silently aliasing reused bytes.
- **LRU trimming with high/low watermarks.** Free slabs are kept for reuse
  until free bytes exceed ``high_watermark_bytes``; then fully-free regions
  are destroyed in least-recently-used order until free bytes fall to
  ``low_watermark_bytes`` — footprint/lifetime management in the spirit of
  the DNN-serving memory managers (arXiv:2001.03288, arXiv:2308.15152).
- **Cached server registrations.** ``ensure_registered`` keys
  ``register_{system,tpu}_shared_memory`` by ``(endpoint url, region)``:
  an RPC is issued only on a region's FIRST use against that endpoint,
  then cached until invalidated (endpoint ejection/reconnect via
  :meth:`ShmArena.invalidate_endpoint` — the pool wires this to its
  ejection events — or a server-side unregister, which the frontends
  report via :func:`notify_unregister`). Registration RPCs per request
  amortize to ~0.

The transparent fast path is wired at the client layer
(``InferInput.set_data_from_numpy(..., arena=...)`` stages straight into a
slab; a client configured with ``shm_arena=`` promotes staged binary inputs
into leases at ``infer()`` time and ``InferResult.as_numpy`` returns a
zero-copy view over the slab). See docs/tpu_shared_memory.md "Arena & lease
lifecycle".
"""

from __future__ import annotations

import asyncio
import atexit
import hashlib
import threading
import uuid as _uuid
import weakref
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import flight as _flight
from . import observe as _observe
from .utils import (
    serialize_bf16_tensor,
    serialize_byte_tensor,
    triton_to_np_dtype,
)
from .utils.shared_memory import SharedMemoryException

__all__ = [
    "ArenaError",
    "ArenaLeaseReleased",
    "ArenaLease",
    "LeaseDigest",
    "ShmArena",
    "default_arena",
    "arenas",
    "notify_unregister",
    "bind_request",
    "bind_request_async",
]

_PAGE = 4096


class ArenaError(SharedMemoryException):
    """Raised on arena lifecycle misuse (double release, closed arena, ...)."""


class ArenaLeaseReleased(ArenaError):
    """A zero-copy view/read was requested from a lease after its last
    ``release()`` — the slab may already back a different lease."""


def _round_class(nbytes: int, min_class: int, max_class: int) -> int:
    """The size class serving ``nbytes``: next power of two clamped to
    [min_class, max_class]; oversize requests get a page-rounded class of
    their own (reused only by same-class leases)."""
    if nbytes > max_class:
        return (nbytes + _PAGE - 1) // _PAGE * _PAGE
    c = min_class
    while c < nbytes:
        c <<= 1
    return c


class LeaseDigest:
    """A blake2b-128 seal over the first ``nbytes`` of a lease's slab.

    Sealed when a response lands (output leases under an integrity
    policy with ``digests=True``; ``disagg``'s KV handoff) and
    re-verified at ``as_numpy()`` map time — a server that scribbles
    over shared memory AFTER answering is caught before the first read.
    The digest rides the lease object itself: no extra RPCs, ever.
    """

    DIGEST_SIZE = 16  # blake2b-128, matching disagg's KV handoff seal

    __slots__ = ("nbytes", "hexdigest")

    def __init__(self, nbytes: int, hexdigest: str):
        self.nbytes = nbytes
        self.hexdigest = hexdigest

    @classmethod
    def seal(cls, lease: "ArenaLease",
             nbytes: Optional[int] = None) -> "LeaseDigest":
        n = nbytes if nbytes is not None else (lease.nbytes
                                               or lease.byte_size)
        view = lease.memoryview()[:n]
        return cls(n, hashlib.blake2b(
            view, digest_size=cls.DIGEST_SIZE).hexdigest())

    def compute(self, lease: "ArenaLease") -> str:
        """The current content digest over this seal's span."""
        view = lease.memoryview()[:self.nbytes]
        return hashlib.blake2b(
            view, digest_size=self.DIGEST_SIZE).hexdigest()

    def verify(self, lease: "ArenaLease", url: str = "") -> None:
        """Re-hash and compare; mismatch raises a typed ``digest``
        ``integrity.IntegrityError`` (and counts into the process
        integrity stats so doctor/perf surface it)."""
        actual = self.compute(lease)
        if actual != self.hexdigest:
            from . import integrity as _integrity

            _integrity.global_stats().record_violation("digest", url)
            _flight.note("integrity", "violation", kind="digest",
                         url=url, field=lease.region_name)
            raise _integrity.IntegrityError(
                "digest", url, lease.region_name, self.hexdigest, actual)


class _ArenaRegion:
    """One large mapped region carved into same-class slabs."""

    __slots__ = (
        "family", "name", "key", "class_bytes", "slab_count", "byte_size",
        "handle", "free_count", "leased", "last_used", "registered",
        "device_id",
    )

    def __init__(self, family: str, name: str, key: str, class_bytes: int,
                 slab_count: int, handle: Any, device_id: int):
        self.family = family
        self.name = name
        self.key = key
        self.class_bytes = class_bytes
        self.slab_count = slab_count
        self.byte_size = class_bytes * slab_count
        self.handle = handle
        # free-slab OFFSETS live only in the arena's per-class freelist;
        # the region keeps a count (inventory/trim need nothing more)
        self.free_count = 0
        self.leased = 0
        self.last_used = 0                 # arena sequence number (LRU order)
        # endpoint url -> weakref(client) for best-effort unregister at trim
        self.registered: Dict[str, Any] = {}
        self.device_id = device_id

    def _host_view(self) -> memoryview:
        if self.family == "system":
            return self.handle.buf()
        return self.handle.host_buffer()


class ArenaLease:
    """A ref-counted hold on one slab of an arena region.

    Created with one reference; ``retain()`` adds holders, ``release()``
    drops one — the slab returns to the arena's free list when the count
    reaches zero. All data accessors raise :class:`ArenaLeaseReleased`
    once fully released.
    """

    __slots__ = ("_arena", "_region", "_offset", "_nbytes", "_refs",
                 "_digest")

    def __init__(self, arena: "ShmArena", region: _ArenaRegion, offset: int,
                 nbytes: int):
        self._arena = arena
        self._region = region
        self._offset = offset
        self._nbytes = nbytes
        self._refs = 1
        self._digest: Optional[LeaseDigest] = None

    # -- identity ----------------------------------------------------------
    @property
    def arena(self) -> "ShmArena":
        return self._arena

    @property
    def family(self) -> str:
        return self._region.family

    @property
    def region_name(self) -> str:
        return self._region.name

    @property
    def region_key(self) -> str:
        return self._region.key

    @property
    def offset(self) -> int:
        return self._offset

    @property
    def byte_size(self) -> int:
        """The slab's class size (the lease may use only a prefix of it)."""
        return self._region.class_bytes

    @property
    def nbytes(self) -> int:
        """Bytes actually staged/requested (<= byte_size)."""
        return self._nbytes

    @property
    def released(self) -> bool:
        return self._refs <= 0

    def __repr__(self) -> str:
        return (f"ArenaLease(region={self.region_name!r}, offset={self._offset}"
                f", class={self.byte_size}, nbytes={self._nbytes}, "
                f"refs={self._refs})")

    # -- integrity seal ----------------------------------------------------
    def seal_digest(self, nbytes: Optional[int] = None) -> LeaseDigest:
        """Seal the slab's current contents (first ``nbytes``, default the
        staged span) under a :class:`LeaseDigest`; every later
        ``as_numpy`` re-verifies it before mapping. A local ``write*``
        drops the seal (the holder mutating its own slab is not
        corruption)."""
        self._digest = LeaseDigest.seal(self, nbytes)
        return self._digest

    def digest(self) -> Optional[LeaseDigest]:
        return self._digest

    # -- refcount ----------------------------------------------------------
    def retain(self) -> "ArenaLease":
        self._arena._retain(self)
        return self

    def release(self) -> None:
        self._arena._release(self)

    # -- data --------------------------------------------------------------
    def _check_live(self) -> None:
        if self._refs <= 0:
            raise ArenaLeaseReleased(
                f"arena lease on {self.region_name!r}@{self._offset} was "
                "released; the slab may already back another lease")

    def _check_span(self, nbytes: int, offset: int, op: str) -> None:
        if offset < 0 or nbytes < 0 or offset + nbytes > self.byte_size:
            raise ArenaError(
                f"arena lease {op} of {nbytes}B at offset {offset} exceeds "
                f"the {self.byte_size}B slab")

    def memoryview(self) -> memoryview:
        """A writable view of the whole slab (zero-copy). On tpu-family
        regions, overlapping device entries are flushed into the window
        and dropped first, so the raw view is coherent both ways."""
        self._check_live()
        base = self._offset
        if self._region.family == "tpu":
            self._region.handle._flush_overlapping(base, self.byte_size)
        return self._region._host_view()[base: base + self.byte_size]

    def _pre_host_write(self, base: int, nbytes: int) -> None:
        # tpu-family regions: a pinned device entry is authoritative over
        # its host range — drop overlapping entries so a direct host write
        # cannot be shadowed (or later clobbered by a flush) by stale
        # device bytes from a previous occupant of this slab
        if self._region.family == "tpu":
            self._region.handle._invalidate_overlapping(base, nbytes)

    def _pre_host_read(self, base: int, nbytes: int) -> None:
        # the mirror of _pre_host_write: materialize overlapping device
        # entries into the host window before a host-side read
        if self._region.family == "tpu":
            self._region.handle._flush_overlapping(base, nbytes)

    def write(self, data, offset: int = 0) -> int:
        """Copy ``data`` (bytes-like) into the slab; returns bytes written."""
        self._check_live()
        self._digest = None  # a local write invalidates the seal
        data = memoryview(data).cast("B")
        self._check_span(len(data), offset, "write")
        rec = _observe._DATAPLANE
        if rec is not None:
            rec.on_map(self.family, write=True)
        base = self._offset + offset
        self._pre_host_write(base, len(data))
        self._region._host_view()[base: base + len(data)] = data
        if offset + len(data) > self._nbytes:
            self._nbytes = offset + len(data)
        return len(data)

    def write_numpy(self, arr, offset: int = 0) -> int:
        """Serialize a host array into the slab with ONE write (fixed-width
        dtypes are copied directly into the mapping; BYTES/BF16 serialize
        first). Returns bytes written."""
        self._check_live()
        arr = np.asarray(arr)
        if arr.dtype == np.object_ or arr.dtype.kind in ("S", "U"):
            s = serialize_byte_tensor(arr)
            return self.write(s.item() if s.size else b"", offset)
        if arr.dtype == np.dtype(triton_to_np_dtype("BF16")) and \
                arr.dtype != np.float32:
            return self.write(serialize_bf16_tensor(arr).item(), offset)
        nbytes = arr.nbytes
        self._check_span(nbytes, offset, "write")
        self._digest = None  # a local write invalidates the seal
        rec = _observe._DATAPLANE
        if rec is not None:
            rec.on_map(self.family, write=True)
        base = self._offset + offset
        self._pre_host_write(base, nbytes)
        dst = np.frombuffer(self._region._host_view(), dtype=np.uint8,
                            count=nbytes, offset=base)
        np.copyto(dst, np.ascontiguousarray(arr).view(np.uint8).reshape(-1))
        if offset + nbytes > self._nbytes:
            self._nbytes = offset + nbytes
        return nbytes

    def write_jax(self, array, offset: int = 0, timers=None) -> int:
        """Bind a jax.Array at the lease's slab (tpu-family regions only):
        pins the device buffer in the region's cache and mirrors to host
        unless the region is colocated. Returns bytes written."""
        self._check_live()
        self._digest = None  # a local write invalidates the seal
        if self.family != "tpu":
            raise ArenaError("write_jax needs a tpu-family lease")
        from .utils.tpu_shared_memory import set_shared_memory_region_from_jax

        nbytes = array.dtype.itemsize * array.size
        self._check_span(nbytes, offset, "write")
        set_shared_memory_region_from_jax(
            self._region.handle, array, self._offset + offset, timers)
        if offset + nbytes > self._nbytes:
            self._nbytes = offset + nbytes
        return nbytes

    def as_numpy(self, datatype, shape, offset: int = 0) -> np.ndarray:
        """Decode the slab contents as ``datatype``/``shape``.

        Fixed-width dtypes return a ZERO-COPY view over the mapped region —
        the view is valid only while the lease is held, and requesting it
        after the last ``release()`` raises :class:`ArenaLeaseReleased`.
        BYTES/BF16 decode (one copy, as everywhere else).
        """
        self._check_live()
        if self._digest is not None:
            # sealed lease: re-verify the server's answer before mapping
            # (a post-answer scribble raises typed, never aliases garbage)
            self._digest.verify(self)
        if isinstance(datatype, str):
            triton_dtype = datatype
            np_dtype = (np.dtype(np.object_) if datatype == "BYTES"
                        else np.dtype(triton_to_np_dtype(datatype)))
        else:
            np_dtype = np.dtype(datatype)
            triton_dtype = "BYTES" if np_dtype == np.object_ else None
        rec = _observe._DATAPLANE
        if rec is not None:
            rec.on_map(self.family, write=False)
        n_elems = int(np.prod(shape)) if len(shape) else 1
        base = self._offset + offset
        if triton_dtype == "BYTES":
            from .utils import deserialize_bytes_tensor

            span = self._offset + self.byte_size - base
            self._pre_host_read(base, span)
            raw = bytes(self._region._host_view()[base: base + span])
            return deserialize_bytes_tensor(raw, count=n_elems).reshape(shape)
        if triton_dtype == "BF16":
            from .utils import deserialize_bf16_tensor

            self._pre_host_read(base, 2 * n_elems)
            raw = bytes(self._region._host_view()[base: base + 2 * n_elems])
            return deserialize_bf16_tensor(raw).reshape(shape)
        nbytes = n_elems * np_dtype.itemsize
        self._check_span(nbytes, offset, "read")
        self._pre_host_read(base, nbytes)
        return np.frombuffer(self._region._host_view(), dtype=np_dtype,
                             count=n_elems, offset=base).reshape(shape)

    def as_jax(self, datatype, shape, offset: int = 0, timers=None):
        """Device view of the slab (tpu-family): cache hit = the pinned
        jax.Array, zero copies; miss = one H2D ``device_put``."""
        self._check_live()
        if self.family != "tpu":
            raise ArenaError("as_jax needs a tpu-family lease")
        from .utils.tpu_shared_memory import get_contents_as_jax

        return get_contents_as_jax(
            self._region.handle, datatype, shape, self._offset + offset,
            timers)

    # -- request binding ---------------------------------------------------
    def bind_input(self, inp) -> Any:
        """Point an ``InferInput`` at this lease's slab (releases any
        OTHER lease the input previously held — re-binding the same lease
        is idempotent, not a self-release) and attach for
        registration-on-infer."""
        self._check_live()
        if getattr(inp, "_arena_lease", None) is self:
            inp._arena_lease = None  # set_shared_memory must not drop US
        inp.set_shared_memory(self.region_name, self._nbytes or self.byte_size,
                              self._offset)
        inp._arena_lease = self
        return inp

    def bind_output(self, out) -> Any:
        """Point an ``InferRequestedOutput`` at this lease's slab
        (re-binding the same lease is idempotent)."""
        self._check_live()
        if getattr(out, "_arena_lease", None) is self:
            out._arena_lease = None
        out.set_shared_memory(self.region_name, self.byte_size, self._offset)
        out._arena_lease = self
        return out


class ShmArena:
    """The pooled allocator over both shm util packages.

    One arena serves BOTH families: ``lease(nbytes, family="system")`` for
    POSIX host regions, ``family="tpu"`` for TPU host-window regions (with
    the arena's ``device_id``/``colocated`` settings). All public methods
    are thread-safe; lease/release never block beyond one short lock, so
    they are safe on asyncio event loops too.
    """

    def __init__(
        self,
        default_family: str = "system",
        min_class_bytes: int = _PAGE,
        max_class_bytes: int = 64 * 1024 * 1024,
        region_target_bytes: int = 1024 * 1024,
        max_slabs_per_region: int = 64,
        high_watermark_bytes: int = 256 * 1024 * 1024,
        low_watermark_bytes: int = 128 * 1024 * 1024,
        device_id: int = 0,
        colocated: bool = True,
        promote_inputs: bool = True,
        name_prefix: str = "arena",
    ):
        if default_family not in ("system", "tpu"):
            raise ArenaError(f"unknown shm family {default_family!r}")
        if min_class_bytes <= 0 or max_class_bytes < min_class_bytes:
            raise ArenaError("invalid size-class bounds")
        if low_watermark_bytes > high_watermark_bytes:
            raise ArenaError("low watermark must not exceed the high one")
        self.default_family = default_family
        self.min_class_bytes = min_class_bytes
        self.max_class_bytes = max_class_bytes
        self.region_target_bytes = region_target_bytes
        self.max_slabs_per_region = max_slabs_per_region
        self.high_watermark_bytes = high_watermark_bytes
        self.low_watermark_bytes = low_watermark_bytes
        self.device_id = device_id
        self.colocated = colocated
        self.promote_inputs = promote_inputs
        self.name_prefix = name_prefix
        self._lock = threading.Lock()
        self._seq = 0
        self._closed = False
        # (family, class_bytes) -> [(region, offset), ...] free slabs
        self._free: Dict[Tuple[str, int], List[Tuple[_ArenaRegion, int]]] = {}
        self._regions: List[_ArenaRegion] = []
        self._free_bytes = 0
        self._total_bytes = 0
        # (url, region name) registration cache + per-key issue locks
        self._registered: set = set()
        self._reg_locks: Dict[Tuple[str, str], threading.Lock] = {}
        self._stats = {
            "leases": 0, "releases": 0, "hits": 0, "misses": 0,
            "regions_created": 0, "regions_trimmed": 0,
            "registrations_issued": 0, "registrations_cached": 0,
            "registrations_invalidated": 0,
        }
        _ARENAS.add(self)

    # -- allocation --------------------------------------------------------
    def _class_for(self, nbytes: int) -> int:
        return _round_class(nbytes, self.min_class_bytes, self.max_class_bytes)

    def _carve_locked(self, family: str, class_bytes: int) -> _ArenaRegion:
        """Create one region carved into slabs of ``class_bytes`` (caller
        holds the lock; the mmap itself is microseconds)."""
        slabs = 1
        if class_bytes <= self.region_target_bytes:
            slabs = max(1, min(self.max_slabs_per_region,
                               self.region_target_bytes // class_bytes))
        name = f"{self.name_prefix}_{family}_{_uuid.uuid4().hex[:12]}"
        total = class_bytes * slabs
        if family == "system":
            from .utils import shared_memory as shm

            handle = shm.create_shared_memory_region(
                name, f"/{name}", total, create_only=True)
            key = f"/{name}"
        else:
            from .utils import tpu_shared_memory as tpushm

            handle = tpushm.create_shared_memory_region(
                name, total, device_id=self.device_id,
                colocated=self.colocated)
            key = handle.shm_key
        region = _ArenaRegion(family, name, key, class_bytes, slabs, handle,
                              self.device_id)
        self._regions.append(region)
        self._total_bytes += total
        self._free_bytes += total
        freelist = self._free.setdefault((family, class_bytes), [])
        for i in range(slabs):
            freelist.append((region, i * class_bytes))
        region.free_count = slabs
        self._stats["regions_created"] += 1
        rec = _observe._DATAPLANE
        if rec is not None:
            rec.on_arena_carve(family, class_bytes, slabs)
        return region

    def lease(self, nbytes: int, family: Optional[str] = None) -> ArenaLease:
        """Lease one slab of the size class serving ``nbytes``.

        Returns an :class:`ArenaLease` holding ONE reference. A free slab
        of the class is a hit (no syscalls at all); a cold class carves a
        new region once and every subsequent lease hits."""
        if nbytes <= 0:
            raise ArenaError("lease size must be positive")
        family = family or self.default_family
        if family not in ("system", "tpu"):
            raise ArenaError(f"unknown shm family {family!r}")
        class_bytes = self._class_for(nbytes)
        with self._lock:
            if self._closed:
                raise ArenaError("arena is closed")
            freelist = self._free.get((family, class_bytes))
            if freelist:
                hit = True
            else:
                self._carve_locked(family, class_bytes)
                freelist = self._free[(family, class_bytes)]
                hit = False
            region, offset = freelist.pop()
            region.free_count -= 1
            region.leased += 1
            self._seq += 1
            region.last_used = self._seq
            self._free_bytes -= class_bytes
            self._stats["leases"] += 1
            self._stats["hits" if hit else "misses"] += 1
        rec = _observe._DATAPLANE
        if rec is not None:
            rec.on_arena_lease(family, class_bytes, hit)
        _flight.note("arena", "lease", bytes=class_bytes, hit=hit)
        return ArenaLease(self, region, offset, nbytes)

    def _retain(self, lease: ArenaLease) -> None:
        with self._lock:
            if lease._refs <= 0:
                raise ArenaLeaseReleased(
                    "cannot retain a fully released arena lease")
            lease._refs += 1

    def _release(self, lease: ArenaLease) -> None:
        trim: List[_ArenaRegion] = []
        with self._lock:
            if lease._refs <= 0:
                raise ArenaError(
                    f"arena lease on {lease.region_name!r}@{lease.offset} "
                    "released more times than retained")
            lease._refs -= 1
            if lease._refs > 0:
                return
            region = lease._region
            # a freed slab must not carry its occupant's pinned device
            # tensors into the next lease (they would shadow/clobber fresh
            # host writes) — evict BEFORE the slab is published to the
            # free list, or a concurrent re-lease's write_jax pin could be
            # the thing we drop (lock order arena -> region handle is
            # taken nowhere in reverse)
            if region.family == "tpu":
                region.handle._invalidate_overlapping(
                    lease._offset, region.class_bytes)
            region.free_count += 1
            region.leased -= 1
            self._seq += 1
            region.last_used = self._seq
            self._free.setdefault((region.family, region.class_bytes), []) \
                .append((region, lease._offset))
            self._free_bytes += region.class_bytes
            self._stats["releases"] += 1
            if self._free_bytes > self.high_watermark_bytes:
                trim = self._collect_trim_locked(self.low_watermark_bytes)
        rec = _observe._DATAPLANE
        if rec is not None:
            rec.on_arena_release(region.family, region.class_bytes)
        if trim:
            self._trim_async(trim)

    # -- trimming ----------------------------------------------------------
    def _collect_trim_locked(self, target_free_bytes: int) -> List[_ArenaRegion]:
        """Pick fully-free regions LRU-first until free bytes fall to the
        target; detach them from the arena's structures (caller destroys
        outside the lock)."""
        victims: List[_ArenaRegion] = []
        idle = sorted((r for r in self._regions if r.leased == 0),
                      key=lambda r: r.last_used)
        for region in idle:
            if self._free_bytes <= target_free_bytes:
                break
            self._regions.remove(region)
            freelist = self._free.get((region.family, region.class_bytes), [])
            self._free[(region.family, region.class_bytes)] = [
                slot for slot in freelist if slot[0] is not region]
            self._free_bytes -= region.byte_size
            self._total_bytes -= region.byte_size
            for url in region.registered:
                self._registered.discard((url, region.name))
            self._stats["regions_trimmed"] += 1
            victims.append(region)
        return victims

    def _trim_async(self, victims: List[_ArenaRegion]) -> None:
        """Watermark trims fire from ``release()``, which promises never to
        block (asyncio callers release on the event loop): the best-effort
        unregister RPCs and munmaps run on a short-lived daemon thread.
        The victims are already detached from every arena structure, so
        nothing can re-lease them meanwhile."""
        threading.Thread(
            target=self._destroy_regions, args=(victims,),
            name="shm-arena-trim", daemon=True).start()

    def _destroy_regions(self, regions: List[_ArenaRegion]) -> None:
        for region in regions:
            # best-effort server-side unregister everywhere this region was
            # registered (a dead client weakref or an async-only client just
            # means the server keeps a stale attach until its own cleanup)
            for url, ref in list(region.registered.items()):
                client = ref() if ref is not None else None
                if client is None:
                    continue
                unregister = getattr(
                    client,
                    "unregister_system_shared_memory"
                    if region.family == "system"
                    else "unregister_tpu_shared_memory", None)
                if unregister is None or asyncio.iscoroutinefunction(unregister):
                    continue
                try:
                    unregister(region.name)
                except Exception:
                    pass
            try:
                if region.family == "system":
                    from .utils import shared_memory as shm

                    shm.destroy_shared_memory_region(region.handle)
                else:
                    from .utils import tpu_shared_memory as tpushm

                    tpushm.destroy_shared_memory_region(region.handle)
            except Exception:
                pass
            rec = _observe._DATAPLANE
            if rec is not None:
                rec.on_arena_trim(region.family, region.class_bytes,
                                  region.slab_count)

    def trim(self, target_free_bytes: int = 0) -> int:
        """Destroy fully-free regions (LRU-first) until free bytes fall to
        ``target_free_bytes``; returns the number of regions destroyed."""
        with self._lock:
            victims = self._collect_trim_locked(target_free_bytes)
        self._destroy_regions(victims)
        return len(victims)

    def close(self, force: bool = False) -> None:
        """Destroy every region. Outstanding leases make this an error
        unless ``force=True`` (their views die with the mappings)."""
        with self._lock:
            leased = sum(r.leased for r in self._regions)
            if leased and not force:
                raise ArenaError(
                    f"cannot close arena: {leased} slab(s) still leased "
                    "(pass force=True to tear down anyway)")
            victims = list(self._regions)
            self._regions.clear()
            self._free.clear()
            self._free_bytes = 0
            self._total_bytes = 0
            self._registered.clear()
            self._reg_locks.clear()
            self._closed = True
        self._destroy_regions(victims)

    # -- cached server registrations ---------------------------------------
    @staticmethod
    def _endpoint_of(client) -> str:
        url = getattr(client, "_url", None)
        return url if url else f"anon:{id(client):x}"

    def _issue_register(self, client, region: _ArenaRegion):
        """The actual registration RPC (whole region, offset 0: every slab
        rides one registration)."""
        if region.family == "system":
            return client.register_system_shared_memory(
                region.name, region.key, region.byte_size)
        from .utils import tpu_shared_memory as tpushm

        return client.register_tpu_shared_memory(
            region.name, tpushm.get_raw_handle(region.handle),
            region.device_id, region.byte_size)

    def _note_cached(self) -> None:
        with self._lock:
            self._stats["registrations_cached"] += 1
        rec = _observe._DATAPLANE
        if rec is not None:
            rec.on_arena_registration("cached")

    def _note_issued(self, url: str, region: _ArenaRegion, client) -> None:
        with self._lock:
            self._registered.add((url, region.name))
            try:
                region.registered[url] = weakref.ref(client)
            except TypeError:
                region.registered[url] = None
            self._stats["registrations_issued"] += 1
        rec = _observe._DATAPLANE
        if rec is not None:
            rec.on_arena_registration("issued")
        # a registration RPC on the request path is exactly the kind of
        # one-off stall a retained slow timeline should explain
        _flight.note("arena", "register", url=url, region=region.name)

    def is_registered(self, client, region_name: str) -> bool:
        with self._lock:
            return (self._endpoint_of(client), region_name) in self._registered

    def ensure_registered(self, client, region: _ArenaRegion) -> bool:
        """Make ``region`` usable against ``client``'s endpoint; the RPC is
        issued only on first use (True) — every later call is a cache hit
        (False, no network)."""
        url = self._endpoint_of(client)
        ck = (url, region.name)
        with self._lock:
            if ck in self._registered:
                cached = True
            else:
                cached = False
                issue_lock = self._reg_locks.setdefault(ck, threading.Lock())
        if cached:
            self._note_cached()
            return False
        with issue_lock:
            with self._lock:
                if ck in self._registered:
                    cached = True
            if cached:
                self._note_cached()
                return False
            try:
                self._issue_register(client, region)
            except Exception as e:
                # Triton semantics: re-registering an active name errors.
                # Region names are uuid-unique, so "already registered" can
                # only mean the server still holds OUR registration (e.g.
                # cache invalidated while the server kept state) — adopt it.
                if "already" not in str(e).lower():
                    raise
            self._note_issued(url, region, client)
        with self._lock:
            self._reg_locks.pop(ck, None)
        return True

    async def ensure_registered_async(self, client, region: _ArenaRegion) -> bool:
        """Asyncio twin of :meth:`ensure_registered` (optimistic: a rare
        concurrent first use may double-issue; the server's
        "already registered" answer is adopted as success)."""
        url = self._endpoint_of(client)
        ck = (url, region.name)
        with self._lock:
            if ck in self._registered:
                cached = True
            else:
                cached = False
        if cached:
            self._note_cached()
            return False
        try:
            await self._issue_register(client, region)
        except Exception as e:
            if "already" not in str(e).lower():
                raise
        self._note_issued(url, region, client)
        return True

    def invalidate_endpoint(self, url: str) -> int:
        """Drop every cached registration against ``url`` (the pool calls
        this on ejection; reconnect-class faults mean the server may have
        restarted and lost its registrations). Returns entries dropped."""
        with self._lock:
            dropped = [ck for ck in self._registered if ck[0] == url]
            for ck in dropped:
                self._registered.discard(ck)
            for region in self._regions:
                region.registered.pop(url, None)
            self._stats["registrations_invalidated"] += len(dropped)
        rec = _observe._DATAPLANE
        if rec is not None:
            for _ in dropped:
                rec.on_arena_registration("invalidated")
        return len(dropped)

    def _on_server_unregister(self, url: Optional[str], name: str) -> None:
        """A frontend reported a successful server-side unregister: drop the
        matching cache entries (name == "" unregisters ALL of that url's)."""
        if url is None:
            return
        with self._lock:
            if name:
                if (url, name) not in self._registered:
                    return
                dropped = [(url, name)]
            else:
                dropped = [ck for ck in self._registered if ck[0] == url]
            if not dropped:
                return
            for ck in dropped:
                self._registered.discard(ck)
            for region in self._regions:
                if not name or region.name == name:
                    region.registered.pop(url, None)
            self._stats["registrations_invalidated"] += len(dropped)
        rec = _observe._DATAPLANE
        if rec is not None:
            for _ in dropped:
                rec.on_arena_registration("invalidated")

    # -- convenience -------------------------------------------------------
    def stage(self, data, family: Optional[str] = None) -> ArenaLease:
        """Lease a slab sized for ``data`` (bytes-like) and write it in one
        call — the response cache (``client_tpu.cache``) stages each cached
        output's payload this way, so the entry outlives the wire buffer
        for exactly as long as the lease is held. The lease is released on
        a failed write (no slab can leak half-staged)."""
        view = memoryview(data).cast("B")
        lease = self.lease(max(len(view), 1), family=family)
        try:
            if len(view):
                lease.write(view)
        except BaseException:
            lease.release()
            raise
        return lease

    def request_output(self, name: str, nbytes: int,
                       family: Optional[str] = None):
        """An ``InferRequestedOutput`` backed by a fresh lease: the server
        writes the output into the slab and ``InferResult.as_numpy``
        returns a zero-copy view pinned by the lease."""
        from ._tensor import InferRequestedOutput

        lease = self.lease(nbytes, family=family)
        return lease.bind_output(InferRequestedOutput(name))

    # -- read side ---------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        """JSON-ready counters + residency (the perf rows' arena hit rate
        and the doctor's leak check read this)."""
        with self._lock:
            s = dict(self._stats)
            s["leased_bytes"] = self._total_bytes - self._free_bytes
            s["free_bytes"] = self._free_bytes
            s["total_bytes"] = self._total_bytes
            s["regions"] = len(self._regions)
            s["leased_slabs"] = sum(r.leased for r in self._regions)
            denom = s["leases"]
            s["hit_rate"] = round(s["hits"] / denom, 4) if denom else None
            reg_total = (s["registrations_issued"]
                         + s["registrations_cached"])
            s["registration_cache_hit_rate"] = (
                round(s["registrations_cached"] / reg_total, 4)
                if reg_total else None)
            s["registration_cache_entries"] = len(self._registered)
        return s

    def inventory(self) -> List[Dict[str, Any]]:
        """One dict per region (the doctor's arena section)."""
        with self._lock:
            return [
                {"family": r.family, "name": r.name, "key": r.key,
                 "class_bytes": r.class_bytes, "slabs": r.slab_count,
                 "byte_size": r.byte_size, "leased_slabs": r.leased,
                 "free_slabs": r.free_count,
                 "registered_endpoints": sorted(r.registered)}
                for r in self._regions
            ]

    def registration_entries(self) -> Dict[str, List[str]]:
        """Cached registrations grouped per endpoint url."""
        out: Dict[str, List[str]] = {}
        with self._lock:
            for url, name in sorted(self._registered):
                out.setdefault(url, []).append(name)
        return out


# live arenas (doctor inventory + server-unregister fan-out)
_ARENAS: "weakref.WeakSet[ShmArena]" = weakref.WeakSet()
_default_arena: Optional[ShmArena] = None
_default_lock = threading.Lock()


def _close_all_at_exit() -> None:
    """Arena regions deliberately outlive individual requests and runs, so
    unmap+unlink them at interpreter exit (otherwise the multiprocessing
    resource tracker warns about — and then unlinks — every one)."""
    for arena in arenas():
        try:
            arena.close(force=True)
        except Exception:
            pass


atexit.register(_close_all_at_exit)


def arenas() -> List[ShmArena]:
    """Every live arena in this process."""
    return list(_ARENAS)


def default_arena(**kwargs) -> ShmArena:
    """The process-default arena (created on first use; ``shm_arena=True``
    on a client resolves to it). ``kwargs`` configure the first creation
    only."""
    global _default_arena
    with _default_lock:
        if _default_arena is None or _default_arena._closed:
            _default_arena = ShmArena(**kwargs)
        return _default_arena


def notify_unregister(url: Optional[str], name: str = "") -> None:
    """Called by the frontends after a successful server-side unregister
    RPC so every arena's registration cache stops assuming the region is
    still registered there."""
    for arena in arenas():
        arena._on_server_unregister(url, name)


# -- request binding (the frontends' transparent fast path) -------------------
_SHM_PARAM_KEYS = ("shared_memory_region", "shared_memory_byte_size",
                   "shared_memory_offset")


class _BoundRequest:
    """Per-request arena bookkeeping handed back to the frontend: restores
    promoted inputs and releases their transient leases after the response
    (``settle``), and attaches user-leased output leases to the result
    (``finish``) so ``as_numpy`` can serve zero-copy views."""

    __slots__ = ("_promoted", "_out_leases", "_seal_digests")

    def __init__(self):
        self._promoted: List[Tuple[Any, Any, ArenaLease]] = []
        self._out_leases: Optional[Dict[str, ArenaLease]] = None
        self._seal_digests = False

    def finish(self, result) -> None:
        if self._out_leases:
            result._arena_output_leases = dict(self._out_leases)
            if self._seal_digests:
                # seal each output slab the moment the response lands:
                # as_numpy re-verifies, so a server scribbling after its
                # answer raises typed instead of aliasing garbage
                for lease in self._out_leases.values():
                    if not lease.released:
                        lease.seal_digest()

    def settle(self) -> None:
        for inp, raw, lease in self._promoted:
            for key in _SHM_PARAM_KEYS:
                inp._parameters.pop(key, None)
            inp._raw_data = raw
            try:
                lease.release()
            except ArenaError:
                pass
        self._promoted = []


def _promote_input(arena: ShmArena, inp, raw) -> Tuple[ArenaLease, Any]:
    """Stage an input's already-serialized binary payload into a slab and
    swap its wire representation to shm params (restored by settle)."""
    lease = arena.lease(len(raw), family=arena.default_family)
    try:
        lease.write(raw)
    except BaseException:
        lease.release()
        raise
    inp._raw_data = None
    inp._parameters["shared_memory_region"] = lease.region_name
    inp._parameters["shared_memory_byte_size"] = len(raw)
    if lease.offset:
        inp._parameters["shared_memory_offset"] = lease.offset
    inp._parameters.pop("binary_data_size", None)
    return lease, raw


def _collect(client, arena: Optional[ShmArena], inputs, outputs,
             promote: bool):
    """Shared scan: (ensure list of (arena, region), ctx or None)."""
    # validation pass BEFORE any mutation: a released lease's slab may
    # already back another live lease, so refusing here turns silent
    # cross-request corruption into the typed error (reusing a request
    # object after release_arena/release_arena_lease requires re-staging)
    # — and raising before promotion means no transient lease can leak
    for tensor in list(inputs) + list(outputs or ()):
        lease = getattr(tensor, "_arena_lease", None)
        if lease is not None:
            lease._check_live()
    ctx: Optional[_BoundRequest] = None
    ensure: List[Tuple[ShmArena, _ArenaRegion]] = []
    for inp in inputs:
        lease = getattr(inp, "_arena_lease", None)
        if lease is not None:
            ensure.append((lease.arena, lease._region))
            continue
        if not promote or arena is None or not arena.promote_inputs:
            continue
        raw = getattr(inp, "_raw_data", None)
        if not raw:
            continue
        lease, saved = _promote_input(arena, inp, raw)
        ensure.append((arena, lease._region))
        if ctx is None:
            ctx = _BoundRequest()
        ctx._promoted.append((inp, saved, lease))
    for out in outputs or ():
        lease = getattr(out, "_arena_lease", None)
        if lease is None:
            continue
        ensure.append((lease.arena, lease._region))
        if ctx is None:
            ctx = _BoundRequest()
        if ctx._out_leases is None:
            ctx._out_leases = {}
        ctx._out_leases[out.name()] = lease
    if ctx is not None and ctx._out_leases:
        # opt-in data-plane digests: seal output slabs at finish time
        # when the owning client's integrity policy asks for them
        policy_of = getattr(client, "integrity_policy", None)
        if policy_of is not None:
            policy = policy_of()
            ctx._seal_digests = policy is not None and policy.digests
    return ensure, ctx


def bind_request(client, arena: Optional[ShmArena], inputs, outputs,
                 promote: bool = True) -> Optional[_BoundRequest]:
    """Bind one outgoing request to the arena data plane (sync frontends):
    promote staged binary inputs into leases, and make sure every touched
    region is registered against this client's endpoint (cached after the
    first RPC). Returns None when the request touches no arena state."""
    ensure, ctx = _collect(client, arena, inputs, outputs, promote)
    try:
        for owner, region in ensure:
            owner.ensure_registered(client, region)
    except BaseException:
        if ctx is not None:
            ctx.settle()
        raise
    return ctx


async def bind_request_async(client, arena: Optional[ShmArena], inputs,
                             outputs, promote: bool = True
                             ) -> Optional[_BoundRequest]:
    """Asyncio twin of :func:`bind_request` for the aio frontends."""
    ensure, ctx = _collect(client, arena, inputs, outputs, promote)
    try:
        for owner, region in ensure:
            await owner.ensure_registered_async(client, region)
    except BaseException:
        if ctx is not None:
            ctx.settle()
        raise
    return ctx
