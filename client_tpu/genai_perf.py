"""LLM streaming perf harness: TTFT / inter-token latency / token throughput.

The reference ecosystem measures LLM serving with genai-perf (the
perf_analyzer companion that moved out-of-repo with it —
/root/reference/src/c++/perf_analyzer/genai-perf/README.md): time to first
token, inter-token latency, output token throughput, request throughput,
per session-concurrency level. This is that tool for the tpu-native stack,
built on the framework's own streaming GRPC client.

Two serving styles, matching the two LLM fixtures:

- ``decoupled`` (default, model ``tiny_lm_generate``): one request carries
  the prompt + MAX_TOKENS and the server streams one response per
  generated token — the Triton TensorRT-LLM/vLLM backend shape. TTFT is
  send→first streamed response (prefill + first decode step + wire); each
  subsequent gap is one inter-token latency.
- ``sequence`` (model ``decoder_lm``): the client drives decoding one
  token per request over the stateful sequence API (sequence_id +
  start/end), feeding each NEXT_TOKEN back. Same metrics; the ITL now
  includes a full client round trip per token — measuring exactly what
  client-side decoding costs vs server-side generation.

Usage:
    python -m client_tpu.genai_perf -u 127.0.0.1:8001 \
        --concurrency-range 1:4 --sessions 20 \
        --prompt-tokens 32 --output-tokens 32

Prints one JSON list (``-f json``) or a table; exit 1 if any level
produced zero completed sessions.
"""

from __future__ import annotations

import argparse
import json
import queue
import sys
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from .perf import _percentile


def _summary(values: List[float]) -> Dict[str, float]:
    vs = sorted(values)
    return {
        "avg": round(sum(vs) / len(vs), 3) if vs else 0.0,
        "p50": round(_percentile(vs, 0.50), 3),
        "p90": round(_percentile(vs, 0.90), 3),
        "p99": round(_percentile(vs, 0.99), 3),
    }


class _Session:
    """Per-session measurement record (all times perf_counter seconds).

    ``tel_ttft``/``tel_itl`` (ms) are the StreamSpan-sourced twins of the
    stopwatch measurements, populated when telemetry is armed."""

    __slots__ = ("start", "first", "last", "tokens", "error",
                 "tel_ttft", "tel_itl")

    def __init__(self):
        self.start = 0.0
        self.first: Optional[float] = None
        self.last = 0.0
        self.tokens = 0
        self.error: Optional[str] = None
        self.tel_ttft: Optional[float] = None
        self.tel_itl: Optional[float] = None


class GenAiPerfRunner:
    """Drives N concurrent generation sessions and aggregates LLM metrics."""

    def __init__(self, url: str, model_name: str, mode: str,
                 prompt_tokens: int, output_tokens: int, chunk: int = 1,
                 vocab: int = 256, seed: int = 0, observe: bool = False):
        if mode not in ("decoupled", "sequence", "generate"):
            raise ValueError(f"unknown mode {mode!r}")
        if output_tokens < 1:
            raise ValueError("output_tokens must be >= 1")
        if prompt_tokens < 1:
            raise ValueError("prompt_tokens must be >= 1")
        if observe and mode == "sequence":
            # sequence mode's cleanup send can land a late response after
            # the session's mark window is read — the stopwatch stays the
            # only honest source there
            raise ValueError("--observe supports decoupled/generate modes")
        self.url = url
        self.model_name = model_name
        self.mode = mode
        self.prompt_tokens = prompt_tokens
        self.output_tokens = output_tokens
        self.chunk = chunk
        self.vocab = vocab
        self.seed = seed
        self.telemetry = None
        if observe:
            from .observe import Telemetry

            # sample=off: per-session readings come straight from the
            # client's StreamSpan handle; the ring is not needed and a
            # long sweep must not grow it
            self.telemetry = Telemetry(sample="off")

    # -- one session ---------------------------------------------------------
    def _prompt(self, rng: np.random.Generator) -> np.ndarray:
        return rng.integers(
            0, self.vocab, size=(1, self.prompt_tokens), dtype=np.int32)

    def _run_decoupled_session(self, client, InferInput, sess: _Session,
                               responses: "queue.Queue",
                               rng: np.random.Generator) -> None:
        """One request → stream of per-token responses until the final
        marker (triton_enable_empty_final_response semantics)."""
        tokens_in = InferInput("TOKENS", [1, self.prompt_tokens], "INT32")
        tokens_in.set_data_from_numpy(self._prompt(rng))
        max_in = InferInput("MAX_TOKENS", [1], "INT32")
        max_in.set_data_from_numpy(
            np.array([self.output_tokens], dtype=np.int32))
        params = {"chunk": self.chunk} if self.chunk != 1 else None

        # telemetry window: the stream's span marks every response; this
        # session's marks are the ones appended after n0 (sessions run
        # sequentially per worker stream)
        span = client.stream_span() if self.telemetry is not None else None
        n0 = span.chunk_count if span is not None else 0
        sess.start = time.perf_counter()
        start_ns = time.perf_counter_ns()
        client.async_stream_infer(
            self.model_name, [tokens_in, max_in],
            enable_empty_final_response=True,
            parameters=params,
        )
        while True:
            result, error = responses.get(timeout=120.0)
            now = time.perf_counter()
            if error is not None:
                sess.error = str(error) or "stream error"
                return
            if result.is_final_response() and result.is_null_response():
                sess.last = sess.last or now
                break
            if sess.first is None:
                sess.first = now
            sess.last = now
            sess.tokens += 1
        if span is not None and sess.tokens:
            # marks include the empty final-response frame: the session's
            # token marks are the first `tokens` entries of its window
            marks = span.marks_ns()[n0:][:sess.tokens]
            if marks:
                sess.tel_ttft = (marks[0] - start_ns) / 1e6
            if len(marks) > 1:
                sess.tel_itl = (marks[-1] - marks[0]) / 1e6 / (len(marks) - 1)

    def _run_generate_session(self, client, sess: _Session,
                              rng: np.random.Generator) -> None:
        """One generate-extension SSE stream over HTTP — the transport the
        reference genai-perf drives against tritonserver's
        extension_generate endpoints. Same metrics as decoupled mode; the
        per-token gap now includes SSE framing + chunked HTTP delivery.

        Fully-consumed streams release their connection back to the pool
        (generate_stream's exhausted path), so per-session TTFT measures
        the protocol, not a fresh TCP handshake — keeping the committed
        decoupled-vs-generate comparison fair against the long-lived GRPC
        stream modes (only abandoned/error sessions pay a reconnect)."""
        inputs: Dict[str, Any] = {
            "TOKENS": self._prompt(rng).tolist(),
            "MAX_TOKENS": self.output_tokens,
        }
        params = {"chunk": self.chunk} if self.chunk != 1 else None
        sess.start = time.perf_counter()
        for _event in client.generate_stream(
            self.model_name, inputs, parameters=params
        ):
            now = time.perf_counter()
            if sess.first is None:
                sess.first = now
            sess.last = now
            sess.tokens += 1
        if self.telemetry is not None:
            # single source of truth: the session IS one StreamSpan —
            # TTFT/ITL come from its marks, not this loop's stopwatch
            span = client.last_stream_span()
            if span is not None:
                ttfts = span.ttft_ms_per_attempt()
                if ttfts:
                    sess.tel_ttft = ttfts[0]
                itls = span.itl_values_ms()
                if itls:
                    sess.tel_itl = sum(itls) / len(itls)

    def _run_sequence_session(self, client, InferInput, sess: _Session,
                              responses: "queue.Queue", sequence_id: int,
                              rng: np.random.Generator) -> None:
        """Client-driven decode loop over the stateful sequence API.

        Always closes the sequence: the server keeps per-sequence KV caches
        until a sequence_end arrives (decoder.py state map), so an aborted
        session must still send end=True or every error leaks a cache."""
        ended = False

        def send(tokens: np.ndarray, start: bool, end: bool):
            nonlocal ended
            inp = InferInput("TOKENS", list(tokens.shape), "INT32")
            inp.set_data_from_numpy(tokens)
            client.async_stream_infer(
                self.model_name, [inp], sequence_id=sequence_id,
                sequence_start=start, sequence_end=end)
            ended = ended or end

        def recv() -> Optional[int]:
            result, error = responses.get(timeout=120.0)
            if error is not None:
                sess.error = str(error) or "stream error"
                return None
            return int(result.as_numpy("NEXT_TOKEN").reshape(-1)[0])

        try:
            sess.start = time.perf_counter()
            send(self._prompt(rng), start=True, end=self.output_tokens == 1)
            nxt = recv()
            if nxt is None:
                return
            now = time.perf_counter()
            sess.first = sess.last = now
            sess.tokens = 1
            while sess.tokens < self.output_tokens:
                last = sess.tokens + 1 >= self.output_tokens
                send(np.array([[nxt]], dtype=np.int32), start=False, end=last)
                nxt = recv()
                if nxt is None:
                    return
                sess.last = time.perf_counter()
                sess.tokens += 1
        finally:
            if not ended:
                # best-effort server-side state cleanup; whatever response
                # or error this produces lands in a queue the worker
                # discards (error paths rebuild the stream + queue)
                try:
                    send(np.array([[0]], dtype=np.int32), start=False, end=True)
                except Exception:
                    pass

    # -- one concurrency level ----------------------------------------------
    def run(self, concurrency: int, sessions: int) -> Dict[str, Any]:
        from .grpc import InferenceServerClient, InferInput

        done: List[_Session] = []
        done_lock = threading.Lock()
        counter = {"n": 0}
        seq_counter = {"n": int(time.time()) % 100000 * 1000}
        barrier = threading.Barrier(concurrency + 1)

        def worker(worker_id: int):
            # numpy Generators are not thread-safe: one independent
            # stream per worker (seeded deterministically per id)
            rng = np.random.default_rng((self.seed, worker_id))
            # the callback reads the queue through this holder so a stream
            # rebuild can swap in a fresh queue atomically
            holder = {"q": queue.Queue()}
            client = None
            setup_error: Optional[str] = None
            try:
                if self.mode == "generate":
                    from .http import InferenceServerClient as HttpClient

                    client = HttpClient(self.url)
                    if self.telemetry is not None:
                        client.configure_telemetry(self.telemetry)
                else:
                    client = InferenceServerClient(self.url)
                    if self.telemetry is not None:
                        # before start_stream: the stream span must exist
                        # from the first session's first response
                        client.configure_telemetry(self.telemetry)
                    client.start_stream(
                        lambda result, error: holder["q"].put((result, error)))
            except Exception as e:
                # keep the thread alive through barrier.wait() — dying here
                # would strand run() on the barrier forever
                setup_error = f"worker setup failed: {e}"
            try:
                barrier.wait()
                while True:
                    with done_lock:
                        if counter["n"] >= sessions:
                            return
                        counter["n"] += 1
                        seq_counter["n"] += 1
                        seq_id = seq_counter["n"]
                    sess = _Session()
                    if setup_error is not None:
                        sess.error = setup_error
                    else:
                        try:
                            if self.mode == "decoupled":
                                self._run_decoupled_session(
                                    client, InferInput, sess, holder["q"],
                                    rng)
                            elif self.mode == "generate":
                                self._run_generate_session(client, sess, rng)
                            else:
                                self._run_sequence_session(
                                    client, InferInput, sess, holder["q"],
                                    seq_id, rng)
                        except Exception as e:  # survive one bad session
                            sess.error = str(e) or type(e).__name__
                        if sess.error is not None and self.mode != "generate":
                            # the broken session's late responses may still
                            # be in flight: cancel the stream, then swap in
                            # a fresh queue so the next session can't
                            # consume another session's tokens
                            try:
                                client.stop_stream(cancel_requests=True)
                            except Exception:
                                pass
                            holder["q"] = queue.Queue()
                            try:
                                client.start_stream(
                                    lambda result, error:
                                    holder["q"].put((result, error)))
                            except Exception as e:
                                setup_error = f"stream restart failed: {e}"
                    with done_lock:
                        done.append(sess)
            finally:
                if client is not None:
                    try:
                        if self.mode != "generate":
                            client.stop_stream()
                        client.close()
                    except Exception:
                        pass

        threads = [
            threading.Thread(target=worker, args=(i,), daemon=True)
            for i in range(concurrency)
        ]
        for t in threads:
            t.start()
        barrier.wait()
        t0 = time.perf_counter()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0

        ok = [s for s in done if s.error is None and s.first is not None]
        errors = [s for s in done if s.error is not None]
        # error-free sessions that streamed zero tokens: neither completed
        # nor errored — dropping them from both buckets silently
        # undercounted (they break the tokens-received contract, so they
        # count toward the nonzero exit the same way errors do)
        incomplete = [s for s in done
                      if s.error is None and s.first is None]
        ttft_ms = [(s.first - s.start) * 1e3 for s in ok]
        e2e_ms = [(s.last - s.start) * 1e3 for s in ok]
        itl_ms: List[float] = []
        for s in ok:
            if s.tokens > 1:
                itl_ms.append((s.last - s.first) * 1e3 / (s.tokens - 1))
        total_tokens = sum(s.tokens for s in ok)
        result = {
            "mode": self.mode,
            "model": self.model_name,
            "concurrency": concurrency,
            "sessions": len(ok),
            "errors": len(errors),
            "incomplete": len(incomplete),
            "error_sample": errors[0].error if errors else None,
            "prompt_tokens": self.prompt_tokens,
            "output_tokens": self.output_tokens,
            "chunk": self.chunk,
            "wall_s": round(wall, 3),
            "ttft_ms": _summary(ttft_ms),
            "inter_token_ms": _summary(itl_ms),
            "e2e_ms": _summary(e2e_ms),
            "output_tokens_per_sec": round(total_tokens / wall, 1) if wall else 0.0,
            "requests_per_sec": round(len(ok) / wall, 2) if wall else 0.0,
        }
        if self.telemetry is not None:
            self._telemetry_result(result, ok, ttft_ms, itl_ms)
        return result

    # the stopwatch re-measures what the StreamSpan already recorded: its
    # only job with telemetry armed is to BOUND the span's numbers. Agree-
    # ment within the floor validates both; divergence beyond it flags a
    # broken clock path, not noise.
    TELEMETRY_NOISE_FLOOR_MS = 2.0
    TELEMETRY_NOISE_FLOOR_FRAC = 0.10

    def _telemetry_result(self, result: Dict[str, Any], ok: List[_Session],
                          sw_ttft: List[float], sw_itl: List[float]) -> None:
        """Emit the StreamSpan-sourced TTFT/ITL as the headline numbers
        (single source of truth), keep the stopwatch twins for the A/B,
        and flag divergence beyond the noise floor."""
        tel_ttft = [s.tel_ttft for s in ok if s.tel_ttft is not None]
        tel_itl = [s.tel_itl for s in ok if s.tel_itl is not None]
        if not tel_ttft:
            result["telemetry_source"] = None
            return
        result["telemetry_source"] = "stream_span"
        result["ttft_ms_stopwatch"] = result["ttft_ms"]
        result["inter_token_ms_stopwatch"] = result["inter_token_ms"]
        result["ttft_ms"] = _summary(tel_ttft)
        result["inter_token_ms"] = _summary(tel_itl)
        divergence = {}
        warned = False
        for key, sw in (("ttft_p50_ms", result["ttft_ms_stopwatch"]),
                        ("itl_p50_ms", result["inter_token_ms_stopwatch"])):
            tel_summary = (result["ttft_ms"] if key.startswith("ttft")
                           else result["inter_token_ms"])
            delta = round(tel_summary["p50"] - sw["p50"], 3)
            divergence[key] = delta
            floor = max(self.TELEMETRY_NOISE_FLOOR_MS,
                        self.TELEMETRY_NOISE_FLOOR_FRAC * abs(sw["p50"]))
            if abs(delta) > floor:
                warned = True
        result["telemetry_divergence_ms"] = divergence
        result["telemetry_warning"] = warned


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="client_tpu.genai_perf",
        description="LLM streaming perf: TTFT / inter-token latency / token throughput",
    )
    parser.add_argument("-u", "--url", default="127.0.0.1:8001",
                        help="GRPC endpoint (decoupled/sequence) or HTTP "
                             "endpoint (generate mode)")
    parser.add_argument("-m", "--model-name", default=None,
                        help="default: tiny_lm_generate (decoupled/generate)"
                             " / decoder_lm (sequence)")
    parser.add_argument("--mode", choices=("decoupled", "sequence", "generate"),
                        default="decoupled",
                        help="decoupled: GRPC bi-di stream; generate: the "
                             "HTTP generate-extension SSE endpoint (what "
                             "the reference genai-perf drives); sequence: "
                             "client-driven stateful decode")
    parser.add_argument("--concurrency-range", default="1",
                        help="start[:end[:step]] concurrent sessions")
    parser.add_argument("--sessions", type=int, default=20,
                        help="measured sessions per concurrency level")
    parser.add_argument("--prompt-tokens", type=int, default=32)
    parser.add_argument("--output-tokens", type=int, default=32)
    parser.add_argument("--chunk", type=int, default=1,
                        help="tokens per device dispatch (decoupled mode)")
    parser.add_argument("--warmup-sessions", type=int, default=2)
    parser.add_argument(
        "--observe", action="store_true",
        help="arm client telemetry and source TTFT/ITL from the "
             "StreamSpan instead of this tool's stopwatch (both are "
             "emitted; divergence beyond the noise floor is flagged). "
             "decoupled/generate modes only")
    parser.add_argument("-f", "--format", choices=("table", "json"),
                        default="table")
    args = parser.parse_args(argv)

    model = args.model_name or (
        "decoder_lm" if args.mode == "sequence" else "tiny_lm_generate")
    parts = [int(x) for x in args.concurrency_range.split(":")]
    start = parts[0]
    end = parts[1] if len(parts) > 1 else start
    step = parts[2] if len(parts) > 2 else 1

    runner = GenAiPerfRunner(
        args.url, model, args.mode, args.prompt_tokens, args.output_tokens,
        chunk=args.chunk, observe=args.observe)
    if args.warmup_sessions:
        runner.run(1, args.warmup_sessions)

    results = []
    for concurrency in range(start, end + 1, step):
        results.append(runner.run(concurrency, args.sessions))

    for r in results:
        if r.get("telemetry_warning"):
            print(
                f"WARNING: concurrency {r['concurrency']}: StreamSpan vs "
                f"stopwatch divergence beyond the noise floor: "
                f"{r['telemetry_divergence_ms']}", file=sys.stderr)

    if args.format == "json":
        print(json.dumps(results))
    else:
        print(f"model={model} mode={args.mode} prompt={args.prompt_tokens} "
              f"max_tokens={args.output_tokens} chunk={args.chunk}")
        print(f"{'conc':>5} {'sess':>5} {'ttft p50':>9} {'ttft p99':>9} "
              f"{'itl p50':>8} {'itl p99':>8} {'tok/s':>8} {'req/s':>7} {'err':>4}")
        for r in results:
            print(f"{r['concurrency']:>5} {r['sessions']:>5} "
                  f"{r['ttft_ms']['p50']:>9} {r['ttft_ms']['p99']:>9} "
                  f"{r['inter_token_ms']['p50']:>8} {r['inter_token_ms']['p99']:>8} "
                  f"{r['output_tokens_per_sec']:>8} {r['requests_per_sec']:>7} "
                  f"{r['errors']:>4}")
    return 1 if any(not r["sessions"] for r in results) else 0


if __name__ == "__main__":
    sys.exit(main())
