"""Multi-cell federation: locality-first spillover, shadow & canary rollout.

One :class:`~client_tpu.pool.PoolClient` stops at one *cell* — one site's
replica fleet. Production deployments run several cells (zones, racks,
shared facilities) and two failure shapes the single-cell stack cannot
absorb: a WHOLE cell saturating or blackholing (admission sheds become
user-visible errors instead of traffic moving somewhere healthy), and a
bad model rollout (a new version burning its SLO with no automatic way
back). This module closes ROADMAP item 5:

- :class:`FederatedClient` / :class:`AioFederatedClient` — the familiar
  ``InferenceServerClient`` surface over NAMED cells, each cell an
  existing pool client, so resilience, admission, the shm arena, caching,
  batching and flight recording all compose unchanged *underneath*::

      from client_tpu.federation import FederatedClient

      fed = FederatedClient(
          {"us-a": ["10.0.0.1:8000", "10.0.0.2:8000"],
           "us-b": ["10.1.0.1:8000", "10.1.0.2:8000"]},
          home="us-a", protocol="http")
      fed.infer("simple", inputs)       # home cell; spills when it can't

- **Locality-first spillover** — traffic goes to the *home* cell; a
  request the home cell cannot serve transparently fails over to the
  next-preferred cell under ONE shared
  :class:`~client_tpu.resilience.AttemptBudget`. Three spill signals:

  * *saturated* — the home pool shed it (typed
    :class:`~client_tpu.admission.AdmissionRejected`:
    ``endpoint_saturated``, lane saturation, queue overflow — see
    ``admission.SPILL_REASONS``). A windowed shed-rate **hysteresis**
    (engage above ``spill_shed_hi``, release below ``spill_shed_lo``)
    flips the cell into *spill-active* so sustained saturation stops
    paying a doomed home attempt per request, and traffic returns home
    only once the pressure genuinely clears.
  * *down* — the per-cell :class:`~client_tpu.resilience.CircuitBreaker`
    is open (fed by fed-level transport outcomes: a cell whose pool
    keeps failing over to nothing opens its breaker and is skipped
    wholesale until a half-open probe proves it back), the pool raised
    ``NoEndpointAvailableError``, or connect-class failures.
  * *blackholed / erroring* — transient/timeout failures that survived
    the pool's own in-cell failover.

  FATAL answers never spill (the server answered; another cell cannot
  help), and sequences never silently cross cells (below).

- **Sequence / stream cell pinning** — a sequence establishes on one
  cell and stays there (server-side sequence state is cell-local); the
  pin may move only while no request of the sequence has landed. An
  in-flight death (or a dead established cell) raises the original
  error and emits a typed :class:`CellSequenceAbandoned` — NEVER a
  silent cross-cell re-send, mirroring the pool's endpoint semantics.
  ``generate_stream`` sessions pin to the cell that produced their
  first event; only a stream that died before delivering anything may
  fail over to the next cell.

- **Shadow mirroring** — ``shadow=ShadowPolicy(cell=..., ratio=...)``
  duplicates a sampled fraction of successful unary infers to a shadow
  cell *off the caller's path*: the mirror runs on a bounded background
  executor AFTER the primary response settles, its response is compared
  bit-for-bit against the primary (the shard-gather exactness rule) and
  only COUNTED (``matched``/``diverged``/``error``) — never returned,
  never billed to the caller's latency, and never to the caller's
  admission token (the mirror rides the shadow cell's own pool).

- **Canary** — ``canary=CanaryPolicy(cell=..., weight=..., slo=...)``
  routes a weighted split of eligible traffic to a canary cell, feeds
  every canary outcome into an :class:`~client_tpu.observe.SLO`
  burn-rate window, and on burn (breached after ``min_events``) ramps
  the weight to ZERO and emits a typed :class:`CanaryRolledBack` —
  automatically, with zero user-visible errors attributable to the
  rollback: a failing canary attempt falls back to the serve plan under
  the same budget instead of raising.

Observability: spills, shadow verdicts and canary transitions export as
``client_tpu_federation_*`` counters plus per-cell gauges
(``Telemetry.attach_federation``), typed events reach ``on_event``,
and the flight recorder gains ``federation``-layer ``route`` /
``cell_spill`` / ``spill_engaged`` / ``canary_route`` /
``canary_rollback`` / ``shadow_mirror`` timeline events. The doctor's
``--cells`` snapshot adds per-cell health and the ``cell_down`` /
``spillover_active`` / ``canary_burning`` anomaly flags. See
docs/federation.md.
"""

from __future__ import annotations

import copy
import random
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import flight as _flight
from ._base import fold_infer_args
from .admission import AdmissionRejected, is_spill_signal
from .pool import AioPoolClient, NoEndpointAvailableError, PoolClient
from .resilience import (
    CONNECT,
    FATAL,
    SHED,
    TIMEOUT,
    TRANSIENT,
    AttemptBudget,
    CircuitBreaker,
    CircuitOpenError,
    ResiliencePolicy,
    RetryPolicy,
    classify_fault,
)
from .utils import InferenceServerException

__all__ = [
    "AioFederatedClient",
    "CanaryPolicy",
    "CanaryRolledBack",
    "CellSequenceAbandoned",
    "CellSpill",
    "CellState",
    "FederatedClient",
    "FederationEvent",
    "NoCellAvailableError",
    "ShadowDiverged",
    "ShadowPolicy",
    "SPILL_DOWN",
    "SPILL_ERROR",
    "SPILL_SATURATED",
    "parse_cells_spec",
]

# spill reasons (the {reason} label on client_tpu_federation_spill_total)
SPILL_SATURATED = "saturated"   # home shed it (admission pressure)
SPILL_DOWN = "down"             # cell breaker open / no endpoint / connect
SPILL_ERROR = "error"           # transient/timeout survived in-cell failover

# cell roles
ROLE_SERVE = "serve"
ROLE_SHADOW = "shadow"
ROLE_CANARY = "canary"


class NoCellAvailableError(InferenceServerException):
    """Every serving cell is breaker-open / down / saturated."""

    def __init__(self, msg: str = "no cell available in the federation"):
        super().__init__(msg, status="FEDERATION_EXHAUSTED")


def parse_cells_spec(spec: str) -> Dict[str, List[str]]:
    """``"a=h1:8000+h2:8000;b=h3:8000"`` -> ``{"a": [...], "b": [...]}``.

    Cells are ``;``-separated ``name=url+url`` groups (``+`` joins a
    cell's replica urls); declaration order is the spill preference
    order, first cell = default home."""
    cells: Dict[str, List[str]] = {}
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        name, eq, urls = part.partition("=")
        name = name.strip()
        if not eq or not name:
            raise ValueError(
                f"malformed cell spec {part!r} (want name=url+url)")
        if name in cells:
            raise ValueError(f"duplicate cell name {name!r}")
        url_list = [u.strip() for u in urls.split("+") if u.strip()]
        if not url_list:
            raise ValueError(f"cell {name!r} declares no urls")
        cells[name] = url_list
    if not cells:
        raise ValueError("cells spec declares no cells")
    return cells


# -- typed federation events --------------------------------------------------
class FederationEvent:
    """Base for events delivered to the federation's ``on_event``."""

    __slots__ = ("cell",)

    def __init__(self, cell: str):
        self.cell = cell

    def __repr__(self) -> str:
        fields = ", ".join(
            f"{name}={getattr(self, name)!r}"
            for cls in type(self).__mro__
            for name in getattr(cls, "__slots__", ()))
        return f"{type(self).__name__}({fields})"


class CellSpill(FederationEvent):
    """A request the home cell could not serve landed on ``target``.
    ``cell`` is the home (preferred) cell, ``reason`` one of the
    ``SPILL_*`` constants."""

    __slots__ = ("target", "reason")

    def __init__(self, cell: str, target: str, reason: str):
        super().__init__(cell)
        self.target = target
        self.reason = reason


class CellSequenceAbandoned(FederationEvent):
    """A sequence pinned to ``cell`` died in flight (or its cell died):
    the federation did NOT re-send it to another cell — cell-local
    sequence state cannot move. The application owns re-driving the
    sequence; the original error still raises."""

    __slots__ = ("request_id", "sequence_id", "cause")

    def __init__(self, cell: str, request_id: str, sequence_id: int,
                 cause: BaseException):
        super().__init__(cell)
        self.request_id = request_id
        self.sequence_id = sequence_id
        self.cause = cause


class ShadowDiverged(FederationEvent):
    """A mirrored request's shadow response did not match the primary
    bit-for-bit. ``output`` names the first mismatching tensor,
    ``detail`` the mismatch class (dtype/shape/values/missing)."""

    __slots__ = ("model", "output", "detail")

    def __init__(self, cell: str, model: str, output: str, detail: str):
        super().__init__(cell)
        self.model = model
        self.output = output
        self.detail = detail


class CanaryRolledBack(FederationEvent):
    """The canary cell's SLO burned: its traffic weight was ramped to
    zero. ``burn_rate`` is the windowed burn at rollback, ``events`` how
    many canary outcomes fed the verdict, ``weight`` the weight that was
    active when the burn tripped."""

    __slots__ = ("burn_rate", "events", "weight")

    def __init__(self, cell: str, burn_rate: float, events: int,
                 weight: float):
        super().__init__(cell)
        self.burn_rate = burn_rate
        self.events = events
        self.weight = weight


# -- rollout policies ---------------------------------------------------------
class ShadowPolicy:
    """Mirror a sampled fraction of successful unary infers to ``cell``.

    ``ratio`` is the sampled fraction (1.0 mirrors everything);
    ``compare`` turns on the bit-for-bit response comparison (off =
    fire-and-count only); ``max_pending`` bounds concurrently in-flight
    mirrors — past it mirrors are SKIPPED (counted), never queued: the
    shadow cell's slowness must not build an unbounded backlog in the
    serving process. ``timeout_s`` bounds each mirror call."""

    def __init__(self, cell: str, ratio: float = 0.01, compare: bool = True,
                 max_pending: int = 8, timeout_s: float = 10.0,
                 rng: Optional[random.Random] = None):
        if not 0.0 < ratio <= 1.0:
            raise ValueError("shadow ratio must be in (0, 1]")
        if max_pending < 1:
            raise ValueError("max_pending must be >= 1")
        self.cell = cell
        self.ratio = float(ratio)
        self.compare = compare
        self.max_pending = int(max_pending)
        self.timeout_s = timeout_s
        self.rng = rng


class CanaryPolicy:
    """Route ``weight`` of eligible traffic to ``cell`` under an SLO
    burn watcher.

    ``slo`` is a latency spec string (``"p95<100ms"`` — ``request_ms``
    metrics only: the canary verdict is caller-visible latency/errors)
    or a prebuilt :class:`~client_tpu.observe.SLO`. Every canary outcome
    feeds it (an error always counts bad); once at least ``min_events``
    outcomes are in and the windowed burn rate exceeds 1.0, the weight
    ramps to ZERO and a typed :class:`CanaryRolledBack` fires — the
    in-flight and subsequent requests serve from the normal plan, so
    the rollback itself causes no user-visible errors. ``window_s``
    bounds the burn window when ``slo`` is a spec string."""

    def __init__(self, cell: str, weight: float = 0.05,
                 slo: Any = "p95<250ms", min_events: int = 20,
                 window_s: float = 60.0,
                 rng: Optional[random.Random] = None):
        if not 0.0 <= weight <= 1.0:
            raise ValueError("canary weight must be in [0, 1]")
        if min_events < 1:
            raise ValueError("min_events must be >= 1")
        self.cell = cell
        self.weight = float(weight)
        self.slo = slo
        self.min_events = int(min_events)
        self.window_s = float(window_s)
        self.rng = rng

    def build_slo(self):
        """Resolve ``slo`` into a live :class:`~client_tpu.observe.SLO`."""
        from .observe import SLO, parse_slo_spec

        if isinstance(self.slo, SLO):
            return self.slo
        spec = parse_slo_spec(str(self.slo))
        if spec.kind != "latency" or spec.metric != "request_ms":
            raise ValueError(
                f"canary slo must be a request-latency objective "
                f"(e.g. 'p95<100ms'), got {self.slo!r}")
        return SLO(f"canary:{self.cell}", "request_ms", spec.threshold_ms,
                   spec.objective, window_s=self.window_s)


class CellState:
    """One named cell: its pool client, cell breaker and spill state.

    Counter mutations happen under the owning federation's lock; the
    shed-rate hysteresis window lives here too (a deque of recent
    home-attempt outcomes, True = shed)."""

    __slots__ = (
        "name", "pool", "role", "breaker", "owns_pool", "served_total",
        "spill_out", "spill_in", "shed_window", "spill_active",
        "sequence_abandoned_total",
    )

    def __init__(self, name: str, pool: Any, role: str = ROLE_SERVE,
                 breaker: Optional[CircuitBreaker] = None,
                 owns_pool: bool = False, shed_window: int = 64):
        self.name = name
        self.pool = pool
        self.role = role
        self.breaker = breaker
        self.owns_pool = owns_pool
        self.served_total = 0
        self.spill_out: Dict[str, int] = {}
        self.spill_in = 0
        self.shed_window: deque = deque(maxlen=shed_window)
        self.spill_active = False
        self.sequence_abandoned_total = 0

    def breaker_admits(self) -> bool:
        return self.breaker is None or self.breaker.would_admit()

    def quarantine_dominated(self) -> bool:
        """More than half this cell's replicas are quarantined for
        contract-violating (byzantine) responses — the plan treats the
        cell as down: a majority of demonstrably-lying replicas is worse
        than a dead cell, and spillover is strictly safer."""
        check = getattr(getattr(self.pool, "pool", None),
                        "quarantine_dominated", None)
        return bool(check()) if check is not None else False

    def record_transport(self, ok: bool) -> None:
        """Feed one fed-level transport outcome into the cell breaker
        (sheds and FATAL answers are NOT transport outcomes)."""
        if self.breaker is not None:
            self.breaker.record(ok)

    def shed_rate(self) -> Optional[float]:
        if not self.shed_window:
            return None
        return sum(self.shed_window) / len(self.shed_window)


def _output_names(result) -> List[str]:
    """Output tensor names of an InferResult (http dict response or the
    grpc codec's decoded message)."""
    try:
        resp = result.get_response()
    except Exception:
        return []
    outputs = (resp.get("outputs", []) if isinstance(resp, dict)
               else getattr(resp, "outputs", []) or [])
    names = []
    for out in outputs:
        name = (out.get("name") if isinstance(out, dict)
                else getattr(out, "name", None))
        if name:
            names.append(name)
    return names


def _compare_results(primary, shadow) -> Optional[Tuple[str, str]]:
    """Shard-style exactness compare: every primary output must exist on
    the shadow with the same dtype, shape and BYTES (bit-for-bit — float
    ``==`` would pass NaN-free near-misses and fail legal NaNs). Returns
    ``None`` on match, else ``(output_name, mismatch_detail)``."""
    names = _output_names(primary)
    if not names:
        return None
    for name in names:
        a = primary.as_numpy(name)
        b = shadow.as_numpy(name)
        if a is None or b is None:
            if (a is None) != (b is None):
                return name, "missing"
            continue
        a = np.asarray(a)
        b = np.asarray(b)
        if a.dtype != b.dtype:
            return name, f"dtype {a.dtype} != {b.dtype}"
        if a.shape != b.shape:
            return name, f"shape {a.shape} != {b.shape}"
        if a.tobytes() != b.tobytes():
            return name, "values"
    return None


class _FederatedBase:
    """Construction + routing/rollout state shared by sync and aio."""

    _AIO = False

    def __init__(
        self,
        cells: Dict[str, Any],
        home: Optional[str] = None,
        preference: Optional[Sequence[str]] = None,
        protocol: str = "http",
        telemetry=None,
        shadow: Optional[ShadowPolicy] = None,
        canary: Optional[CanaryPolicy] = None,
        cell_breaker_factory: Optional[
            Callable[[], Optional[CircuitBreaker]]] = None,
        spill_shed_hi: float = 0.5,
        spill_shed_lo: float = 0.1,
        spill_min_samples: int = 8,
        spill_probe_ratio: float = 0.1,
        shed_window: int = 64,
        default_deadline_s: Optional[float] = None,
        per_attempt_timeout_s: Optional[float] = None,
        rng: Optional[random.Random] = None,
        on_event: Optional[Callable[[FederationEvent], None]] = None,
        pool_kwargs: Optional[Dict[str, Any]] = None,
    ):
        """``cells``: ordered ``{name: PoolClient | [urls]}`` — url lists
        are built into pool clients of the matching flavor (``protocol``
        + ``pool_kwargs`` forwarded, ``telemetry`` shared across every
        cell). ``home`` names the locality-preferred cell (default: the
        first); ``preference`` orders the spill targets (default:
        declaration order). Cells named by ``shadow``/``canary`` leave
        the serve plan: a shadow cell receives only mirrors, a canary
        cell only its weighted split (a down canary must never be a
        spill target — it is the unproven version).

        ``spill_shed_hi``/``spill_shed_lo``: the shed-rate hysteresis
        band over the last ``shed_window`` home attempts (judged once
        ``spill_min_samples`` are in) — engage spill-active at/above
        ``hi``, release at/below ``lo``. While spill-active,
        ``spill_probe_ratio`` of requests still try the home cell first:
        those probes are the only thing that can refresh the shed window
        and RELEASE the hysteresis, so traffic returns home once the
        pressure genuinely clears (0 would latch spill-active forever).

        ``default_deadline_s``/``per_attempt_timeout_s``: the shared
        cross-cell attempt budget (the caller's explicit
        ``client_timeout`` wins)."""
        if not cells:
            raise ValueError("federation needs at least one cell")
        if not 0.0 < spill_shed_lo <= spill_shed_hi <= 1.0:
            raise ValueError(
                "need 0 < spill_shed_lo <= spill_shed_hi <= 1")
        if not 0.0 < spill_probe_ratio <= 1.0:
            raise ValueError(
                "spill_probe_ratio must be in (0, 1]: without home "
                "probes, an engaged spill could never release")
        self.spill_probe_ratio = float(spill_probe_ratio)
        self._shed_window_size = max(2, int(shed_window))
        if cell_breaker_factory is None:
            cell_breaker_factory = CircuitBreaker
        self._telemetry = telemetry
        self._rng = rng or random.Random()
        self._on_event = on_event
        self._lock = threading.Lock()
        self.spill_shed_hi = float(spill_shed_hi)
        self.spill_shed_lo = float(spill_shed_lo)
        self.spill_min_samples = max(1, int(spill_min_samples))
        roles: Dict[str, str] = {}
        if shadow is not None:
            if shadow.cell not in cells:
                raise ValueError(
                    f"shadow cell {shadow.cell!r} is not a declared cell")
            roles[shadow.cell] = ROLE_SHADOW
        if canary is not None:
            if canary.cell not in cells:
                raise ValueError(
                    f"canary cell {canary.cell!r} is not a declared cell")
            if roles.get(canary.cell) == ROLE_SHADOW:
                raise ValueError(
                    "one cell cannot be both shadow and canary")
            roles[canary.cell] = ROLE_CANARY
        built: List[CellState] = []
        self.cells: Dict[str, CellState] = {}
        try:
            for name, value in cells.items():
                role = roles.get(name, ROLE_SERVE)
                if isinstance(value, (list, tuple)):
                    pool = self._build_pool(list(value), protocol,
                                            pool_kwargs or {})
                    owns = True
                else:
                    pool = value
                    owns = False
                state = CellState(name, pool, role=role,
                                  breaker=cell_breaker_factory(),
                                  owns_pool=owns,
                                  shed_window=self._shed_window_size)
                built.append(state)
                self.cells[name] = state
                if telemetry is not None and state.breaker is not None:
                    state.breaker.on_transition = \
                        telemetry.on_breaker_transition
        except Exception:
            self._abandon(built)
            raise
        serve_names = [s.name for s in self.cells.values()
                       if s.role == ROLE_SERVE]
        if not serve_names:
            self._abandon(built)
            raise ValueError(
                "federation needs at least one serving cell (every "
                "declared cell is shadow/canary)")
        self.home = home if home is not None else serve_names[0]
        if self.home not in self.cells:
            self._abandon(built)
            raise ValueError(f"unknown home cell {self.home!r}")
        if self.cells[self.home].role != ROLE_SERVE:
            self._abandon(built)
            raise ValueError(
                f"home cell {self.home!r} must be a serving cell "
                f"(it is {self.cells[self.home].role})")
        if preference is None:
            preference = serve_names
        preference = list(preference)
        unknown = [n for n in preference if n not in self.cells]
        if unknown:
            self._abandon(built)
            raise ValueError(f"unknown cells in preference: {unknown}")
        nonserve = [n for n in preference
                    if self.cells[n].role != ROLE_SERVE]
        if nonserve:
            self._abandon(built)
            raise ValueError(
                f"shadow/canary cells cannot be spill targets: {nonserve}")
        # the serve plan: home first, then the caller's preference order
        self._serve_order: List[CellState] = [self.cells[self.home]] + [
            self.cells[n] for n in preference if n != self.home]
        if default_deadline_s is not None or per_attempt_timeout_s is not None:
            self._budget_policy: Optional[ResiliencePolicy] = \
                ResiliencePolicy(retry=RetryPolicy(
                    max_attempts=1,
                    total_deadline_s=default_deadline_s,
                    per_attempt_timeout_s=per_attempt_timeout_s))
        else:
            self._budget_policy = None
        # -- sequence cell pinning -------------------------------------------
        self._seq_cells: Dict[int, CellState] = {}
        self._seq_established: set = set()
        # -- shadow -----------------------------------------------------------
        self._shadow = shadow
        self._shadow_pending = 0
        self._shadow_stats = {"sent": 0, "matched": 0, "diverged": 0,
                              "errors": 0, "skipped": 0, "uncompared": 0}
        # -- canary -----------------------------------------------------------
        self._canary = canary
        self._canary_slo = canary.build_slo() if canary is not None else None
        self._canary_weight = canary.weight if canary is not None else 0.0
        self._canary_rolled_back = False
        self._canary_stats = {"routed": 0, "ok": 0, "bad": 0,
                              "fallbacks": 0, "rollbacks": 0}
        self._closed = False
        if telemetry is not None and hasattr(telemetry, "attach_federation"):
            telemetry.attach_federation(self)

    # -- construction helpers -------------------------------------------------
    def _build_pool(self, urls: List[str], protocol: str,
                    pool_kwargs: Dict[str, Any]):
        cls = AioPoolClient if self._AIO else PoolClient
        kwargs = dict(pool_kwargs)
        kwargs.setdefault("protocol", protocol)
        if self._telemetry is not None:
            kwargs.setdefault("telemetry", self._telemetry)
        return cls(urls, **kwargs)

    @staticmethod
    def _abandon(states: List[CellState]) -> None:
        for state in states:
            if not state.owns_pool:
                continue
            try:
                result = state.pool.close()
                if hasattr(result, "close"):  # unawaited coroutine
                    result.close()
            except Exception:
                pass

    # -- events / telemetry ----------------------------------------------------
    def emit(self, event: FederationEvent) -> None:
        if self._on_event is None:
            return
        try:
            self._on_event(event)
        except Exception:
            pass  # an observer must never break the data path

    def _tel_spill(self, home: str, target: str, reason: str) -> None:
        tel = self._telemetry
        if tel is not None and hasattr(tel, "on_cell_spill"):
            try:
                tel.on_cell_spill(home, target, reason)
            except Exception:
                pass

    def _tel_shadow(self, outcome: str) -> None:
        tel = self._telemetry
        if tel is not None and hasattr(tel, "on_shadow_result"):
            try:
                tel.on_shadow_result(outcome)
            except Exception:
                pass

    def _tel_canary(self, outcome: str) -> None:
        tel = self._telemetry
        if tel is not None and hasattr(tel, "on_canary"):
            try:
                tel.on_canary(outcome)
            except Exception:
                pass

    # -- spill hysteresis ------------------------------------------------------
    def _note_home_outcome(self, cell: CellState, shed: bool) -> None:
        """Feed one home-cell attempt outcome (shed or served) into the
        cell's shed-rate window and flip the hysteresis state. Emits the
        engage/release transitions onto the flight timeline."""
        with self._lock:
            cell.shed_window.append(shed)
            if len(cell.shed_window) < self.spill_min_samples:
                return
            rate = sum(cell.shed_window) / len(cell.shed_window)
            was = cell.spill_active
            if not was and rate >= self.spill_shed_hi:
                cell.spill_active = True
            elif was and rate <= self.spill_shed_lo:
                cell.spill_active = False
            changed = cell.spill_active != was
            active = cell.spill_active
        if changed:
            _flight.note("federation",
                         "spill_engaged" if active else "spill_released",
                         cell=cell.name, shed_rate=round(rate, 3))

    def _count_spill(self, home: CellState, target: CellState,
                     reason: str) -> None:
        with self._lock:
            home.spill_out[reason] = home.spill_out.get(reason, 0) + 1
            target.spill_in += 1
        _flight.note("federation", "cell_spill", cell=home.name,
                     target=target.name, reason=reason)
        self._tel_spill(home.name, target.name, reason)
        self.emit(CellSpill(home.name, target.name, reason))

    # -- routing plan ----------------------------------------------------------
    @staticmethod
    def _preempt_reason(plan: List[CellState],
                        home: CellState) -> Optional[str]:
        """Why a request that never even TRIES the home cell counts as a
        spill when it lands elsewhere: the home's open breaker filtered
        it from the plan (down), or the shed-rate hysteresis moved it to
        the back (saturated). None = home is first, no preemption."""
        if not plan or plan[0] is home:
            return None
        if home not in plan:
            return SPILL_DOWN
        return SPILL_SATURATED

    def _plan(self) -> List[CellState]:
        """The serve-order candidate cells for one request: home first —
        moved LAST while its shed-rate hysteresis is engaged (still a
        last resort: saturated beats unavailable) — skipping cells whose
        breaker would fast-fail without touching a socket. When every
        cell's breaker is open, the unfiltered order is returned
        (degraded beats self-blinded; each breaker's half-open window
        decides what actually goes through)."""
        order = list(self._serve_order)
        with self._lock:
            if order and order[0].spill_active and len(order) > 1:
                # probe fraction: a sampled slice of traffic keeps trying
                # home first while spill-active — the only feed that can
                # refresh the shed window and release the hysteresis
                if self._rng.random() >= self.spill_probe_ratio:
                    order = order[1:] + order[:1]
        admitted = [c for c in order
                    if c.breaker_admits() and not c.quarantine_dominated()]
        return admitted or order

    # -- sequence pinning helpers ---------------------------------------------
    def _seq_cell(self, sequence_id: int,
                  exclude: Sequence[CellState] = ()) -> CellState:
        with self._lock:
            cell = self._seq_cells.get(sequence_id)
        if cell is not None:
            return cell
        excluded = set(map(id, exclude))
        for candidate in self._plan():
            if id(candidate) not in excluded:
                with self._lock:
                    return self._seq_cells.setdefault(
                        sequence_id, candidate)
        raise NoCellAvailableError()

    def _seq_repin_allowed(self, sequence_id: int) -> bool:
        with self._lock:
            return sequence_id not in self._seq_established

    def _seq_mark_established(self, sequence_id: int) -> None:
        with self._lock:
            self._seq_established.add(sequence_id)

    def _seq_unpin(self, sequence_id: int) -> None:
        with self._lock:
            self._seq_cells.pop(sequence_id, None)
            self._seq_established.discard(sequence_id)

    def _seq_abandon(self, cell: CellState, request_id: str,
                     sequence_id: int, exc: BaseException) -> None:
        with self._lock:
            cell.sequence_abandoned_total += 1
        _flight.note("federation", "sequence_abandoned", cell=cell.name,
                     sequence_id=sequence_id)
        self.emit(CellSequenceAbandoned(cell.name, request_id,
                                        sequence_id, exc))
        self._seq_unpin(sequence_id)

    # -- canary state ----------------------------------------------------------
    def _canary_draw(self, kwargs) -> Optional[CellState]:
        """The canary cell when this request drew the canary split (and
        the canary is armed, not rolled back, and the request eligible —
        unary, non-sequence)."""
        canary = self._canary
        if canary is None or kwargs.get("sequence_id"):
            return None
        with self._lock:
            weight = self._canary_weight
        if weight <= 0.0:
            return None
        rng = canary.rng or self._rng
        if rng.random() >= weight:
            return None
        cell = self.cells[canary.cell]
        if not cell.breaker_admits():
            return None
        return cell

    def _canary_feed(self, latency_s: Optional[float], ok: bool) -> None:
        """Feed one canary outcome into the burn watcher; trips the
        rollback at most once."""
        slo = self._canary_slo
        if slo is None:
            return
        rollback: Optional[CanaryRolledBack] = None
        with self._lock:
            if ok and latency_s is not None:
                slo.observe(latency_s * 1e3)
                self._canary_stats["ok"] += 1
            else:
                slo.observe_failure()
                self._canary_stats["bad"] += 1
            events = self._canary_stats["ok"] + self._canary_stats["bad"]
            if (not self._canary_rolled_back
                    and events >= self._canary.min_events
                    and slo.breached()):
                weight = self._canary_weight
                self._canary_weight = 0.0
                self._canary_rolled_back = True
                self._canary_stats["rollbacks"] += 1
                rollback = CanaryRolledBack(
                    self._canary.cell, round(slo.burn_rate(), 4),
                    events, weight)
        if rollback is not None:
            _flight.note("federation", "canary_rollback",
                         cell=rollback.cell, burn_rate=rollback.burn_rate,
                         events=rollback.events)
            self._tel_canary("rollback")
            self.emit(rollback)

    def canary_arm(self, weight: Optional[float] = None) -> None:
        """Re-arm a rolled-back canary (a NEW rollout decision — never
        automatic). Default weight: the policy's declared weight."""
        if self._canary is None:
            raise InferenceServerException(
                "no canary policy configured", status="FEDERATION_CANARY")
        with self._lock:
            self._canary_weight = (self._canary.weight if weight is None
                                   else float(weight))
            self._canary_rolled_back = False

    def canary_status(self) -> Optional[Dict[str, Any]]:
        if self._canary is None:
            return None
        with self._lock:
            stats = dict(self._canary_stats)
            weight = self._canary_weight
            rolled_back = self._canary_rolled_back
        slo = self._canary_slo
        return {
            "cell": self._canary.cell,
            "weight": weight,
            "declared_weight": self._canary.weight,
            "rolled_back": rolled_back,
            "min_events": self._canary.min_events,
            "slo": slo.name if slo is not None else None,
            "threshold_ms": slo.threshold_ms if slo is not None else None,
            "objective": slo.objective if slo is not None else None,
            "burn_rate": round(slo.burn_rate(), 4) if slo is not None
            else None,
            "breached": slo.breached() if slo is not None else False,
            **stats,
        }

    def shadow_status(self) -> Optional[Dict[str, Any]]:
        if self._shadow is None:
            return None
        with self._lock:
            stats = dict(self._shadow_stats)
            pending = self._shadow_pending
        return {
            "cell": self._shadow.cell,
            "ratio": self._shadow.ratio,
            "compare": self._shadow.compare,
            "pending": pending,
            **stats,
        }

    # -- shared shadow accounting ---------------------------------------------
    def _shadow_should_mirror(self, kwargs) -> bool:
        sp = self._shadow
        if sp is None or kwargs.get("sequence_id"):
            return False
        rng = sp.rng or self._rng
        if rng.random() >= sp.ratio:
            return False
        with self._lock:
            if self._shadow_pending >= sp.max_pending:
                self._shadow_stats["skipped"] += 1
                skipped = True
            else:
                self._shadow_pending += 1
                skipped = False
        if skipped:
            self._tel_shadow("skipped")
            return False
        return True

    @staticmethod
    def _shadow_kwargs(kwargs, timeout_s: float) -> Dict[str, Any]:
        kw = {k: v for k, v in kwargs.items()
              if k not in ("client_timeout", "request_id")}
        kw["client_timeout"] = timeout_s
        return kw

    def _shadow_settle(self, model: str, primary, shadow_result,
                       error: Optional[BaseException]) -> None:
        """Compare + count one finished mirror (runs OFF the caller's
        path). A divergence is retained on its own flight timeline when
        a recorder is armed — the per-request evidence the aggregate
        counter cannot carry."""
        sp = self._shadow
        outcome = "matched"
        mismatch: Optional[Tuple[str, str]] = None
        if error is not None:
            outcome = "error"
        elif sp.compare:
            mismatch = _compare_results(primary, shadow_result)
            if mismatch is not None:
                outcome = "diverged"
        else:
            # compare=False mirrors are fire-and-count only: reporting
            # them as "matched" would claim a bit-identical shadow that
            # was never checked
            outcome = "uncompared"
        with self._lock:
            self._shadow_pending = max(0, self._shadow_pending - 1)
            self._shadow_stats["sent"] += 1
            key = {"matched": "matched", "uncompared": "uncompared",
                   "diverged": "diverged", "error": "errors"}[outcome]
            self._shadow_stats[key] += 1
        self._tel_shadow(outcome)
        if mismatch is not None:
            output, detail = mismatch
            recorder = getattr(self._telemetry, "flight", None) \
                if self._telemetry is not None else None
            if recorder is not None:
                scratch = recorder.begin("federation", model, "shadow")
                if scratch is not None:
                    _flight.note("federation", "shadow_diverged",
                                 cell=sp.cell, output=output, detail=detail)
                    recorder.commit(scratch, error=InferenceServerException(
                        f"shadow diverged on {output!r}: {detail}",
                        status="SHADOW_DIVERGED"))
            self.emit(ShadowDiverged(sp.cell, model, output, detail))

    # -- introspection ---------------------------------------------------------
    def telemetry(self):
        return self._telemetry

    def cell_names(self) -> List[str]:
        return list(self.cells)

    def serve_order(self) -> List[str]:
        """The live serve plan (spill-hysteresis applied) by cell name."""
        return [c.name for c in self._plan()]

    def federation_stats(self) -> Dict[str, Any]:
        """One JSON-ready snapshot: per-cell role/breaker/spill state and
        the pool's aggregated health, plus the shadow and canary views —
        the doctor's ``cells`` section and the bench artifact's evidence
        row both read exactly this."""
        rows: Dict[str, Any] = {}
        with self._lock:
            snap = {
                name: {
                    "role": cell.role,
                    "home": name == self.home,
                    "breaker_state": (cell.breaker.state
                                      if cell.breaker is not None else None),
                    "spill_active": cell.spill_active,
                    "shed_rate": (round(cell.shed_rate(), 4)
                                  if cell.shed_window else None),
                    "served": cell.served_total,
                    "spill_out": dict(cell.spill_out),
                    "spill_in": cell.spill_in,
                    "sequence_abandoned": cell.sequence_abandoned_total,
                }
                for name, cell in self.cells.items()
            }
        for name, row in snap.items():
            health = getattr(self.cells[name].pool, "health_summary", None)
            if health is not None:
                try:
                    row["pool"] = health()
                except Exception:
                    row["pool"] = None
            rows[name] = row
        return {
            "home": self.home,
            "order": [c.name for c in self._serve_order],
            "cells": rows,
            "shadow": self.shadow_status(),
            "canary": self.canary_status(),
        }

    def spill_total(self) -> int:
        with self._lock:
            return sum(n for cell in self.cells.values()
                       for n in cell.spill_out.values())

    def watch_gauges(self) -> Dict[str, Any]:
        """The watchtower's gauge-source contract: how many cells are
        down (breaker open) and their NAMES, plus whether spillover is
        carrying traffic right now."""
        down: List[str] = []
        spill_active = 0
        with self._lock:
            names = list(self.cells)
            for name, cell in self.cells.items():
                if cell.breaker is not None and cell.breaker.state == "open":
                    down.append(name)
                if cell.spill_active:
                    spill_active += 1
        return {
            "cells": len(names),
            "cells_down": len(down),
            "down_cells": sorted(down),
            "spill_active": spill_active,
        }

    # -- surface plumbing ------------------------------------------------------
    def configure_resilience(self, policy):
        raise InferenceServerException(
            "FederatedClient owns per-cell breakers and each cell pool "
            "owns its endpoints' resilience; configure the cells instead")

    def configure_telemetry(self, telemetry):
        raise InferenceServerException(
            "FederatedClient wires telemetry through every cell at "
            "construction; pass telemetry= to the constructor instead")

    # state mutators reach EVERY cell (shadow/canary included: a model or
    # shm registration must exist wherever any traffic can land)
    _BROADCAST_PREFIXES = (
        "register_", "unregister_", "load_model", "unload_model", "update_",
    )

    @classmethod
    def _is_broadcast(cls, name: str) -> bool:
        return any(name.startswith(p) for p in cls._BROADCAST_PREFIXES)


class FederatedClient(_FederatedBase):
    """Synchronous federation over sync pool clients (HTTP or GRPC)."""

    _AIO = False

    def __init__(self, cells, **kwargs):
        super().__init__(cells, **kwargs)
        self._shadow_executor: Optional[ThreadPoolExecutor] = None
        self._shadow_executor_lock = threading.Lock()

    # -- lifecycle -------------------------------------------------------------
    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        with self._shadow_executor_lock:
            if self._shadow_executor is not None:
                self._shadow_executor.shutdown(wait=True)
                self._shadow_executor = None
        for cell in self.cells.values():
            if cell.owns_pool:
                try:
                    cell.pool.close()
                except Exception:
                    pass

    def __enter__(self) -> "FederatedClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def wait_healthy(self, min_healthy: Optional[int] = None,
                     timeout_s: float = 10.0) -> bool:
        """Direct-probe every SERVING cell's pool (see
        ``PoolClient.wait_healthy``); True when every one reached its
        target. Shadow/canary cells are probed too but never fail the
        wait — an absent rollout target must not block serving."""
        ok = True
        for cell in self.cells.values():
            wait = getattr(cell.pool, "wait_healthy", None)
            if wait is None:
                continue
            healthy = wait(min_healthy=min_healthy, timeout_s=timeout_s)
            if cell.role == ROLE_SERVE:
                ok = ok and healthy
        return ok

    # -- inference -------------------------------------------------------------
    def infer(self, model_name: str, inputs, *args, **kwargs):
        """Federated ``infer``: canary split first (when drawn), then the
        locality-first serve plan under one shared attempt budget, with
        the home cell's saturation/availability driving transparent
        spillover. Sequences pin to a cell (below); successful unary
        responses may be shadow-mirrored off-path."""
        kwargs = fold_infer_args(args, kwargs)
        scratch = _flight.layer_begin(self._telemetry, "federation",
                                      model_name)
        if scratch is None:
            return self._infer_fed(model_name, inputs, kwargs)
        try:
            result = self._infer_fed(model_name, inputs, kwargs)
        except BaseException as e:
            _flight.layer_commit(self._telemetry, scratch, error=e)
            raise
        _flight.layer_commit(self._telemetry, scratch)
        return result

    def _infer_fed(self, model_name: str, inputs, kwargs):
        if kwargs.get("sequence_id"):
            return self._sequence_infer(model_name, inputs, kwargs)
        budget = AttemptBudget(self._budget_policy,
                               kwargs.get("client_timeout"))
        canary_cell = self._canary_draw(kwargs)
        if canary_cell is not None:
            served, result = self._canary_attempt(
                canary_cell, model_name, inputs, kwargs, budget)
            if served:
                # canary-served responses are NEVER mirrored: comparing
                # the canary version's output against the shadow cell's
                # baseline version would report every legitimate version
                # difference as a divergence and drown the real signal
                return result
        result = self._serve(model_name, inputs, kwargs, budget)
        self._maybe_shadow(model_name, inputs, kwargs, result)
        return result

    def _canary_attempt(self, cell: CellState, model_name, inputs, kwargs,
                        budget) -> Tuple[bool, Any]:
        """One canary-cell attempt: outcome feeds the burn watcher; a
        failure FALLS BACK to the serve plan (returns (False, None)) so
        canary sickness — and the rollback it triggers — never surfaces
        as a user-visible error."""
        with self._lock:
            self._canary_stats["routed"] += 1
        self._tel_canary("routed")
        _flight.note("federation", "canary_route", cell=cell.name)
        try:
            remaining = budget.attempt_timeout_s()
        except InferenceServerException:
            return False, None  # let the serve plan raise the deadline
        t0 = time.monotonic()
        try:
            kw = dict(kwargs)
            if remaining is not None:
                kw["client_timeout"] = remaining
            result = cell.pool.infer(model_name, inputs, **kw)
        except Exception as e:
            domain = (SHED if isinstance(e, (AdmissionRejected,
                                             CircuitOpenError,
                                             NoEndpointAvailableError))
                      else classify_fault(e))
            if domain in (CONNECT, TRANSIENT, TIMEOUT):
                cell.record_transport(False)
            self._canary_feed(None, ok=False)
            with self._lock:
                self._canary_stats["fallbacks"] += 1
            self._tel_canary("fallback")
            _flight.note("federation", "canary_fallback", cell=cell.name,
                         domain=domain)
            return False, None
        cell.record_transport(True)
        with self._lock:
            cell.served_total += 1
        self._canary_feed(time.monotonic() - t0, ok=True)
        return True, result

    def _serve(self, model_name, inputs, kwargs, budget):
        """The locality-first spill loop over the serve plan."""
        plan = self._plan()
        home = self.cells[self.home]
        reason = self._preempt_reason(plan, home)
        last: Optional[BaseException] = None
        for cell in plan:
            try:
                remaining = budget.attempt_timeout_s()
            except InferenceServerException as deadline_exc:
                if last is not None:
                    raise deadline_exc from last
                raise
            _flight.note("federation", "route", cell=cell.name)
            t0 = time.monotonic()
            try:
                kw = dict(kwargs)
                if remaining is not None:
                    kw["client_timeout"] = remaining
                result = cell.pool.infer(model_name, inputs, **kw)
            except AdmissionRejected as e:
                # the cell shed it: a saturation signal, not a transport
                # outcome (never fed to the cell breaker). Only reasons
                # admission.SPILL_REASONS blesses may move traffic — a
                # future non-capacity rejection must not silently spill.
                if not is_spill_signal(e):
                    raise
                if cell is home:
                    self._note_home_outcome(home, shed=True)
                last, reason = e, SPILL_SATURATED
                _flight.note("federation", "cell_saturated", cell=cell.name,
                             reason=e.reason)
                continue
            except (CircuitOpenError, NoEndpointAvailableError) as e:
                # nothing in the cell can take traffic: count it against
                # the CELL breaker so a dead cell is skipped wholesale
                cell.record_transport(False)
                last, reason = e, SPILL_DOWN
                _flight.note("federation", "cell_down", cell=cell.name)
                continue
            except Exception as e:
                domain = classify_fault(e)
                if domain == FATAL:
                    # the server answered: spilling cannot improve a
                    # request the application already rejected
                    cell.record_transport(True)
                    raise
                if domain == SHED:
                    if cell is home:
                        self._note_home_outcome(home, shed=True)
                    last, reason = e, SPILL_SATURATED
                    continue
                cell.record_transport(False)
                last = e
                reason = SPILL_DOWN if domain == CONNECT else SPILL_ERROR
                _flight.note("federation", "cell_failed", cell=cell.name,
                             domain=domain)
                continue
            cell.record_transport(True)
            with self._lock:
                cell.served_total += 1
            if cell is home:
                self._note_home_outcome(home, shed=False)
            else:
                self._count_spill(home, cell, reason or SPILL_ERROR)
            return result
        if last is not None:
            raise last
        raise NoCellAvailableError()

    # -- sequences -------------------------------------------------------------
    def _sequence_infer(self, model_name, inputs, kwargs):
        """Cell-pinned sequence request: the pin may move only while the
        sequence has no established cell state (connect-class failures of
        a never-landed sequence). An in-flight death abandons the
        sequence with a typed :class:`CellSequenceAbandoned` and raises
        the original error — never a silent cross-cell re-send."""
        sequence_id = kwargs["sequence_id"]
        request_id = kwargs.get("request_id", "")
        budget = AttemptBudget(self._budget_policy,
                               kwargs.get("client_timeout"))
        tried: List[CellState] = []
        last: Optional[BaseException] = None
        for _ in range(len(self._serve_order)):
            try:
                remaining = budget.attempt_timeout_s()
            except InferenceServerException as deadline_exc:
                if last is not None:
                    raise deadline_exc from last
                raise
            cell = self._seq_cell(sequence_id, exclude=tried)
            if cell not in tried:
                tried.append(cell)
            _flight.note("federation", "route", cell=cell.name,
                         sequence_id=sequence_id)
            try:
                kw = dict(kwargs)
                if remaining is not None:
                    kw["client_timeout"] = remaining
                result = cell.pool.infer(model_name, inputs, **kw)
            except AdmissionRejected as e:
                if not is_spill_signal(e):
                    raise  # non-capacity rejections never move traffic
                last = e
                if cell is self.cells[self.home]:
                    self._note_home_outcome(cell, shed=True)
                if self._seq_repin_allowed(sequence_id):
                    # nothing landed yet: the pin (and the sequence) may
                    # start life in the next cell
                    self._seq_unpin(sequence_id)
                    continue
                raise  # established sequences force-admit below; honor it
            except (CircuitOpenError, NoEndpointAvailableError) as e:
                cell.record_transport(False)
                last = e
                if self._seq_repin_allowed(sequence_id):
                    self._seq_unpin(sequence_id)
                    continue
                raise  # one legal cell; nothing was sent — caller retries
            except Exception as e:
                domain = classify_fault(e)
                if domain in (FATAL, SHED):
                    raise
                cell.record_transport(False)
                last = e
                if domain == CONNECT and self._seq_repin_allowed(sequence_id):
                    self._seq_unpin(sequence_id)
                    continue
                # in-flight death (or an established cell's connect
                # failure after the pool burned its own pinned retries):
                # the cell-local sequence state is unknowable — abandon
                self._seq_abandon(cell, request_id, sequence_id, e)
                raise
            cell.record_transport(True)
            with self._lock:
                cell.served_total += 1
            if cell is self.cells[self.home]:
                # a home-served sequence step refreshes the shed window
                # too: a sequence-heavy workload must be able to RELEASE
                # an engaged spill, not latch it forever
                self._note_home_outcome(cell, shed=False)
            self._seq_mark_established(sequence_id)
            if kwargs.get("sequence_end"):
                self._seq_unpin(sequence_id)
            return result
        assert last is not None
        raise last

    # -- streaming -------------------------------------------------------------
    def generate_stream(self, model_name, *args, **kwargs):
        """Federated SSE generate stream: the session pins to the cell
        that produced its FIRST event; a cell that fails before
        delivering anything spills to the next (nothing was consumed, so
        the re-open is safe). After the first event, failures raise —
        generation state is cell-local."""
        plan = self._plan()
        home = self.cells[self.home]
        reason = self._preempt_reason(plan, home)

        def stream():
            last: Optional[BaseException] = None
            spill_reason = reason
            for cell in plan:
                _flight.note("federation", "route", cell=cell.name,
                             op="generate_stream")
                delivered = False
                try:
                    inner = cell.pool.generate_stream(
                        model_name, *args, **kwargs)
                    for item in inner:
                        if not delivered:
                            delivered = True
                            cell.record_transport(True)
                            with self._lock:
                                cell.served_total += 1
                            if cell is home:
                                self._note_home_outcome(home, shed=False)
                            else:
                                self._count_spill(
                                    home, cell,
                                    spill_reason or SPILL_ERROR)
                        yield item
                    return
                except AdmissionRejected as e:
                    if delivered:
                        raise
                    if cell is home:
                        self._note_home_outcome(home, shed=True)
                    last, spill_reason = e, SPILL_SATURATED
                    continue
                except (CircuitOpenError, NoEndpointAvailableError) as e:
                    if delivered:
                        raise
                    cell.record_transport(False)
                    last, spill_reason = e, SPILL_DOWN
                    continue
                except Exception as e:
                    domain = classify_fault(e)
                    if delivered or domain in (FATAL, SHED):
                        raise
                    cell.record_transport(False)
                    last = e
                    spill_reason = (SPILL_DOWN if domain == CONNECT
                                    else SPILL_ERROR)
                    continue
            if last is not None:
                raise last
            raise NoCellAvailableError()

        return stream()

    # -- shadow mirroring ------------------------------------------------------
    def _get_shadow_executor(self) -> ThreadPoolExecutor:
        with self._shadow_executor_lock:
            if self._closed:
                # a submit racing close() must fail HERE (handled below
                # as a skipped mirror), not recreate an executor that
                # nothing will ever shut down
                raise RuntimeError("federation closed")
            if self._shadow_executor is None:
                self._shadow_executor = ThreadPoolExecutor(
                    max_workers=max(2, self._shadow.max_pending),
                    thread_name_prefix="client_tpu_fed_shadow")
            return self._shadow_executor

    def _maybe_shadow(self, model_name, inputs, kwargs, primary) -> None:
        if self._closed or not self._shadow_should_mirror(kwargs):
            return
        sp = self._shadow
        _flight.note("federation", "shadow_mirror", cell=sp.cell)
        # shallow-copy each input: the caller may re-stage the originals
        # the moment this call returns, and the mirror serializes on its
        # own thread (raw-data bytes are immutable, so a shallow copy
        # pins this request's payload)
        try:
            snap = ([copy.copy(i) for i in inputs]
                    if isinstance(inputs, (list, tuple)) else inputs)
        except Exception:
            snap = inputs
        kw = self._shadow_kwargs(kwargs, sp.timeout_s)
        cell = self.cells[sp.cell]

        def mirror():
            error: Optional[BaseException] = None
            result = None
            try:
                result = cell.pool.infer(model_name, snap, **kw)
            except Exception as e:
                error = e
            self._shadow_settle(model_name, primary, result, error)

        try:
            self._get_shadow_executor().submit(mirror)
        except RuntimeError:
            # lost the race with close(): the caller's SUCCESSFUL infer
            # must never pay for a mirror that cannot run — release the
            # pending slot and count the mirror as skipped
            with self._lock:
                self._shadow_pending = max(0, self._shadow_pending - 1)
                self._shadow_stats["skipped"] += 1
            self._tel_shadow("skipped")

    def shadow_drain(self, timeout_s: float = 10.0) -> bool:
        """Block until no mirrors are pending (tests/bench teardown)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if self._shadow_pending == 0:
                    return True
            time.sleep(0.01)
        return False

    # -- generic surface delegation --------------------------------------------
    def _broadcast(self, name: str, args, kwargs):
        first_exc: Optional[BaseException] = None
        result = None
        for cell in self.cells.values():
            try:
                result = getattr(cell.pool, name)(*args, **kwargs)
            except Exception as e:
                if first_exc is None:
                    first_exc = e
        if first_exc is not None:
            raise first_exc
        return result

    def __getattr__(self, name: str):
        if name.startswith("_") or name in ("cells", "home"):
            # the construction-time guard: a lookup of cells/home on a
            # partially-built instance must fail, not recurse through
            # this delegation
            raise AttributeError(name)
        home_pool = self.cells[self.home].pool
        probe = getattr(home_pool, name, None)
        if not callable(probe):
            raise AttributeError(
                f"{type(self).__name__} has no attribute {name!r}")

        if self._is_broadcast(name):
            def call(*args, **kwargs):
                return self._broadcast(name, args, kwargs)
        else:
            def call(*args, **kwargs):
                # read-only/admin calls are locality-first too: the home
                # pool's own failover covers its replicas; a down home
                # cell falls through the serve plan
                last: Optional[BaseException] = None
                for cell in self._plan():
                    try:
                        return getattr(cell.pool, name)(*args, **kwargs)
                    except (CircuitOpenError,
                            NoEndpointAvailableError) as e:
                        last = e
                        continue
                    except Exception as e:
                        if classify_fault(e) in (CONNECT, TRANSIENT,
                                                 TIMEOUT):
                            last = e
                            continue
                        raise
                if last is not None:
                    raise last
                raise NoCellAvailableError()

        call.__name__ = name
        return call


class AioFederatedClient(_FederatedBase):
    """Asyncio twin of :class:`FederatedClient` over aio pool clients.
    Shadow mirrors run as bounded asyncio tasks (truly cancelled at
    close)."""

    _AIO = True

    def __init__(self, cells, **kwargs):
        super().__init__(cells, **kwargs)
        self._shadow_tasks: set = set()

    # -- lifecycle -------------------------------------------------------------
    async def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for task in list(self._shadow_tasks):
            task.cancel()
        for task in list(self._shadow_tasks):
            try:
                await task
            except BaseException:
                pass
        self._shadow_tasks.clear()
        for cell in self.cells.values():
            if cell.owns_pool:
                try:
                    await cell.pool.close()
                except Exception:
                    pass

    async def __aenter__(self) -> "AioFederatedClient":
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    # -- inference -------------------------------------------------------------
    async def infer(self, model_name: str, inputs, *args, **kwargs):
        """Async federated ``infer`` (same routing/rollout contract as
        the sync twin)."""
        kwargs = fold_infer_args(args, kwargs)
        scratch = _flight.layer_begin(self._telemetry, "federation",
                                      model_name)
        if scratch is None:
            return await self._infer_fed(model_name, inputs, kwargs)
        try:
            result = await self._infer_fed(model_name, inputs, kwargs)
        except BaseException as e:
            _flight.layer_commit(self._telemetry, scratch, error=e)
            raise
        _flight.layer_commit(self._telemetry, scratch)
        return result

    async def _infer_fed(self, model_name: str, inputs, kwargs):
        if kwargs.get("sequence_id"):
            return await self._sequence_infer(model_name, inputs, kwargs)
        budget = AttemptBudget(self._budget_policy,
                               kwargs.get("client_timeout"))
        canary_cell = self._canary_draw(kwargs)
        if canary_cell is not None:
            served, result = await self._canary_attempt(
                canary_cell, model_name, inputs, kwargs, budget)
            if served:
                # never mirrored: see the sync twin (version differences
                # are not shadow divergences)
                return result
        result = await self._serve(model_name, inputs, kwargs, budget)
        self._maybe_shadow(model_name, inputs, kwargs, result)
        return result

    async def _canary_attempt(self, cell, model_name, inputs, kwargs,
                              budget) -> Tuple[bool, Any]:
        with self._lock:
            self._canary_stats["routed"] += 1
        self._tel_canary("routed")
        _flight.note("federation", "canary_route", cell=cell.name)
        try:
            remaining = budget.attempt_timeout_s()
        except InferenceServerException:
            return False, None
        t0 = time.monotonic()
        try:
            kw = dict(kwargs)
            if remaining is not None:
                kw["client_timeout"] = remaining
            result = await cell.pool.infer(model_name, inputs, **kw)
        except Exception as e:
            domain = (SHED if isinstance(e, (AdmissionRejected,
                                             CircuitOpenError,
                                             NoEndpointAvailableError))
                      else classify_fault(e))
            if domain in (CONNECT, TRANSIENT, TIMEOUT):
                cell.record_transport(False)
            self._canary_feed(None, ok=False)
            with self._lock:
                self._canary_stats["fallbacks"] += 1
            self._tel_canary("fallback")
            _flight.note("federation", "canary_fallback", cell=cell.name,
                         domain=domain)
            return False, None
        cell.record_transport(True)
        with self._lock:
            cell.served_total += 1
        self._canary_feed(time.monotonic() - t0, ok=True)
        return True, result

    async def _serve(self, model_name, inputs, kwargs, budget):
        plan = self._plan()
        home = self.cells[self.home]
        reason = self._preempt_reason(plan, home)
        last: Optional[BaseException] = None
        for cell in plan:
            try:
                remaining = budget.attempt_timeout_s()
            except InferenceServerException as deadline_exc:
                if last is not None:
                    raise deadline_exc from last
                raise
            _flight.note("federation", "route", cell=cell.name)
            try:
                kw = dict(kwargs)
                if remaining is not None:
                    kw["client_timeout"] = remaining
                result = await cell.pool.infer(model_name, inputs, **kw)
            except AdmissionRejected as e:
                if not is_spill_signal(e):  # see the sync twin
                    raise
                if cell is home:
                    self._note_home_outcome(home, shed=True)
                last, reason = e, SPILL_SATURATED
                _flight.note("federation", "cell_saturated", cell=cell.name,
                             reason=e.reason)
                continue
            except (CircuitOpenError, NoEndpointAvailableError) as e:
                cell.record_transport(False)
                last, reason = e, SPILL_DOWN
                _flight.note("federation", "cell_down", cell=cell.name)
                continue
            except Exception as e:
                domain = classify_fault(e)
                if domain == FATAL:
                    cell.record_transport(True)
                    raise
                if domain == SHED:
                    if cell is home:
                        self._note_home_outcome(home, shed=True)
                    last, reason = e, SPILL_SATURATED
                    continue
                cell.record_transport(False)
                last = e
                reason = SPILL_DOWN if domain == CONNECT else SPILL_ERROR
                _flight.note("federation", "cell_failed", cell=cell.name,
                             domain=domain)
                continue
            cell.record_transport(True)
            with self._lock:
                cell.served_total += 1
            if cell is home:
                self._note_home_outcome(home, shed=False)
            else:
                self._count_spill(home, cell, reason or SPILL_ERROR)
            return result
        if last is not None:
            raise last
        raise NoCellAvailableError()

    async def _sequence_infer(self, model_name, inputs, kwargs):
        sequence_id = kwargs["sequence_id"]
        request_id = kwargs.get("request_id", "")
        budget = AttemptBudget(self._budget_policy,
                               kwargs.get("client_timeout"))
        tried: List[CellState] = []
        last: Optional[BaseException] = None
        for _ in range(len(self._serve_order)):
            try:
                remaining = budget.attempt_timeout_s()
            except InferenceServerException as deadline_exc:
                if last is not None:
                    raise deadline_exc from last
                raise
            cell = self._seq_cell(sequence_id, exclude=tried)
            if cell not in tried:
                tried.append(cell)
            _flight.note("federation", "route", cell=cell.name,
                         sequence_id=sequence_id)
            try:
                kw = dict(kwargs)
                if remaining is not None:
                    kw["client_timeout"] = remaining
                result = await cell.pool.infer(model_name, inputs, **kw)
            except AdmissionRejected as e:
                if not is_spill_signal(e):  # see the sync twin
                    raise
                last = e
                if cell is self.cells[self.home]:
                    self._note_home_outcome(cell, shed=True)
                if self._seq_repin_allowed(sequence_id):
                    self._seq_unpin(sequence_id)
                    continue
                raise
            except (CircuitOpenError, NoEndpointAvailableError) as e:
                cell.record_transport(False)
                last = e
                if self._seq_repin_allowed(sequence_id):
                    self._seq_unpin(sequence_id)
                    continue
                raise
            except Exception as e:
                domain = classify_fault(e)
                if domain in (FATAL, SHED):
                    raise
                cell.record_transport(False)
                last = e
                if domain == CONNECT and self._seq_repin_allowed(sequence_id):
                    self._seq_unpin(sequence_id)
                    continue
                self._seq_abandon(cell, request_id, sequence_id, e)
                raise
            cell.record_transport(True)
            with self._lock:
                cell.served_total += 1
            if cell is self.cells[self.home]:
                # a home-served sequence step refreshes the shed window
                # too: a sequence-heavy workload must be able to RELEASE
                # an engaged spill, not latch it forever
                self._note_home_outcome(cell, shed=False)
            self._seq_mark_established(sequence_id)
            if kwargs.get("sequence_end"):
                self._seq_unpin(sequence_id)
            return result
        assert last is not None
        raise last

    # -- streaming -------------------------------------------------------------
    def generate_stream(self, model_name, *args, **kwargs):
        """Async federated SSE stream (same first-event pinning contract
        as the sync twin)."""
        plan = self._plan()
        home = self.cells[self.home]
        reason = self._preempt_reason(plan, home)

        async def stream():
            last: Optional[BaseException] = None
            spill_reason = reason
            for cell in plan:
                _flight.note("federation", "route", cell=cell.name,
                             op="generate_stream")
                delivered = False
                try:
                    inner = cell.pool.generate_stream(
                        model_name, *args, **kwargs)
                    async for item in inner:
                        if not delivered:
                            delivered = True
                            cell.record_transport(True)
                            with self._lock:
                                cell.served_total += 1
                            if cell is home:
                                self._note_home_outcome(home, shed=False)
                            else:
                                self._count_spill(
                                    home, cell,
                                    spill_reason or SPILL_ERROR)
                        yield item
                    return
                except AdmissionRejected as e:
                    if delivered:
                        raise
                    if cell is home:
                        self._note_home_outcome(home, shed=True)
                    last, spill_reason = e, SPILL_SATURATED
                    continue
                except (CircuitOpenError, NoEndpointAvailableError) as e:
                    if delivered:
                        raise
                    cell.record_transport(False)
                    last, spill_reason = e, SPILL_DOWN
                    continue
                except Exception as e:
                    domain = classify_fault(e)
                    if delivered or domain in (FATAL, SHED):
                        raise
                    cell.record_transport(False)
                    last = e
                    spill_reason = (SPILL_DOWN if domain == CONNECT
                                    else SPILL_ERROR)
                    continue
            if last is not None:
                raise last
            raise NoCellAvailableError()

        return stream()

    # -- shadow mirroring ------------------------------------------------------
    def _maybe_shadow(self, model_name, inputs, kwargs, primary) -> None:
        if self._closed or not self._shadow_should_mirror(kwargs):
            return
        import asyncio

        sp = self._shadow
        _flight.note("federation", "shadow_mirror", cell=sp.cell)
        try:
            snap = ([copy.copy(i) for i in inputs]
                    if isinstance(inputs, (list, tuple)) else inputs)
        except Exception:
            snap = inputs
        kw = self._shadow_kwargs(kwargs, sp.timeout_s)
        cell = self.cells[sp.cell]

        async def mirror():
            error: Optional[BaseException] = None
            result = None
            try:
                result = await cell.pool.infer(model_name, snap, **kw)
            except asyncio.CancelledError:
                # teardown cancel: release the pending slot, count nothing
                with self._lock:
                    self._shadow_pending = max(0, self._shadow_pending - 1)
                raise
            except Exception as e:
                error = e
            self._shadow_settle(model_name, primary, result, error)

        try:
            task = asyncio.get_running_loop().create_task(mirror())
        except RuntimeError:
            # no running loop (shouldn't happen mid-infer): drop the slot
            with self._lock:
                self._shadow_pending = max(0, self._shadow_pending - 1)
            return
        self._shadow_tasks.add(task)
        task.add_done_callback(self._shadow_tasks.discard)

    async def shadow_drain(self, timeout_s: float = 10.0) -> bool:
        import asyncio

        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if self._shadow_pending == 0:
                    return True
            await asyncio.sleep(0.01)
        return False

    # -- generic surface delegation --------------------------------------------
    async def _broadcast(self, name: str, args, kwargs):
        import inspect

        first_exc: Optional[BaseException] = None
        result = None
        for cell in self.cells.values():
            try:
                result = getattr(cell.pool, name)(*args, **kwargs)
                if inspect.isawaitable(result):
                    result = await result
            except Exception as e:
                if first_exc is None:
                    first_exc = e
        if first_exc is not None:
            raise first_exc
        return result

    def __getattr__(self, name: str):
        if name.startswith("_") or name in ("cells", "home"):
            # the construction-time guard: a lookup of cells/home on a
            # partially-built instance must fail, not recurse through
            # this delegation
            raise AttributeError(name)
        home_pool = self.cells[self.home].pool
        probe = getattr(home_pool, name, None)
        if not callable(probe):
            raise AttributeError(
                f"{type(self).__name__} has no attribute {name!r}")

        if self._is_broadcast(name):
            async def call(*args, **kwargs):
                return await self._broadcast(name, args, kwargs)
        else:
            async def call(*args, **kwargs):
                import inspect

                last: Optional[BaseException] = None
                for cell in self._plan():
                    try:
                        result = getattr(cell.pool, name)(*args, **kwargs)
                        if inspect.isawaitable(result):
                            result = await result
                        return result
                    except (CircuitOpenError,
                            NoEndpointAvailableError) as e:
                        last = e
                        continue
                    except Exception as e:
                        if classify_fault(e) in (CONNECT, TRANSIENT,
                                                 TIMEOUT):
                            last = e
                            continue
                        raise
                if last is not None:
                    raise last
                raise NoCellAvailableError()

        call.__name__ = name
        return call
